"""Binned dataset — the HBM-resident column store.

TPU-native redesign of the reference data layer
(`/root/reference/include/LightGBM/dataset.h:280-578`, `src/io/dataset.cpp`):
the reference keeps per-feature-group ``Bin`` objects (dense / sparse /
4-bit / ordered variants) plus EFB bundling; here the whole training matrix
is ONE dense ``[num_rows, num_features]`` int array (uint8 when every
feature has <=256 bins) that lives in HBM, sharded over the mesh data axis
for distributed learners.  Sparse/ordered bin variants are intentionally
dropped — dense gather/scatter is the TPU fast path.  EFB utilities
(`dataset.cpp:48-210` equivalents) live in this module; column merging is
wired into ingest by the learner once histogram feature-groups land.

``Metadata`` mirrors the reference Metadata (`dataset.h:36-248`): labels,
weights, query boundaries, init scores.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..utils.log import log_info, log_warning, check
from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN,
                      MISSING_NONE, MISSING_ZERO, BinMapper)


@dataclass
class Metadata:
    """Per-row side data (reference include/LightGBM/dataset.h:36-248)."""
    label: Optional[np.ndarray] = None           # float32 [n]
    weight: Optional[np.ndarray] = None          # float32 [n]
    query_boundaries: Optional[np.ndarray] = None  # int32 [num_queries+1]
    init_score: Optional[np.ndarray] = None      # float64 [n * num_class]

    @property
    def num_data(self) -> int:
        return 0 if self.label is None else len(self.label)

    def set_field(self, name: str, data) -> None:
        if data is None:
            setattr(self, {"label": "label", "weight": "weight",
                           "group": "query_boundaries", "query": "query_boundaries",
                           "init_score": "init_score"}[name], None)
            return
        arr = np.asarray(data)
        if name == "label":
            self.label = np.ascontiguousarray(arr, dtype=np.float32)
        elif name == "weight":
            self.weight = np.ascontiguousarray(arr, dtype=np.float32)
        elif name in ("group", "query"):
            # accept either per-query sizes or boundaries
            arr = np.ascontiguousarray(arr, dtype=np.int32)
            if len(arr) > 0 and arr[0] == 0:
                self.query_boundaries = arr
            else:
                self.query_boundaries = np.concatenate(
                    [np.zeros(1, np.int32), np.cumsum(arr, dtype=np.int32)])
        elif name == "init_score":
            self.init_score = np.ascontiguousarray(arr, dtype=np.float64)
        else:
            raise ValueError(f"unknown field {name!r}")

    def get_field(self, name: str):
        if name == "label":
            return self.label
        if name == "weight":
            return self.weight
        if name in ("group", "query"):
            return self.query_boundaries
        if name == "init_score":
            return self.init_score
        raise ValueError(f"unknown field {name!r}")

    def check_or_partition(self, num_all_data: int, used_indices: Optional[np.ndarray]) -> None:
        """Subset side-data to used rows (reference dataset.h:82, metadata.cpp)."""
        if used_indices is None:
            return
        if self.label is not None and len(self.label) == num_all_data:
            self.label = self.label[used_indices]
        if self.weight is not None and len(self.weight) == num_all_data:
            self.weight = self.weight[used_indices]
        if self.init_score is not None and len(self.init_score) == num_all_data:
            self.init_score = self.init_score[used_indices]
        if self.query_boundaries is not None:
            self.query_boundaries = _subset_query_boundaries(
                self.query_boundaries, np.asarray(used_indices))


def _subset_query_boundaries(boundaries: np.ndarray,
                             used_indices: np.ndarray) -> np.ndarray:
    """Rebuild query boundaries for a row subset.  Selected rows must keep
    whole queries contiguous (the reference rejects query-splitting
    partitions in Metadata::CheckOrPartition)."""
    qid = np.searchsorted(boundaries, used_indices, side="right") - 1
    if len(qid) and (np.diff(qid) < 0).any():
        raise ValueError("row subset reorders ranking queries")
    sizes = boundaries[1:] - boundaries[:-1]
    taken = np.bincount(qid, minlength=len(sizes))
    partial = (taken > 0) & (taken != sizes)
    if partial.any():
        raise ValueError(
            "row subset splits ranking queries; subset whole queries instead")
    kept_sizes = sizes[taken > 0]
    return np.concatenate([np.zeros(1, np.int32),
                           np.cumsum(kept_sizes, dtype=np.int32)])


# ---------------------------------------------------------------------------
# Exclusive Feature Bundling (reference src/io/dataset.cpp:48-210)
# ---------------------------------------------------------------------------
def _get_conflict_count(mark: np.ndarray, nonzero_rows: np.ndarray,
                        max_cnt: int) -> int:
    """Count rows where this feature and the bundle are both nonzero
    (reference ``GetConfilctCount`` dataset.cpp:48-59); -1 if over budget."""
    cnt = int(mark[nonzero_rows].sum())
    return cnt if cnt <= max_cnt else -1


def find_feature_groups(nonzero_indices: List[np.ndarray], num_rows: int,
                        max_conflict_rate: float,
                        random_order: Optional[np.ndarray] = None) -> List[List[int]]:
    """Greedy graph-coloring of features into low-conflict bundles
    (reference ``FindGroups`` dataset.cpp:66-136)."""
    num_features = len(nonzero_indices)
    order = random_order if random_order is not None else np.arange(num_features)
    group_marks: List[np.ndarray] = []
    group_counts: List[int] = []
    groups: List[List[int]] = []
    total_budget = int(max_conflict_rate * num_rows)
    for fidx in order:
        fidx = int(fidx)
        nz = nonzero_indices[fidx]
        placed = False
        for gid in range(len(groups)):
            rest = total_budget - group_counts[gid]
            cnt = _get_conflict_count(group_marks[gid], nz, rest)
            if cnt >= 0:
                groups[gid].append(fidx)
                group_counts[gid] += cnt
                group_marks[gid][nz] = True
                placed = True
                break
        if not placed:
            mark = np.zeros(num_rows, dtype=bool)
            mark[nz] = True
            groups.append([fidx])
            group_counts.append(0)
            group_marks.append(mark)
    return groups


def fast_feature_bundling(bins: np.ndarray, mappers: List[BinMapper],
                          max_conflict_rate: float, seed: int,
                          sparse_threshold: float = 0.8,
                          max_group_bins: int = 255) -> List[List[int]]:
    """EFB driver (reference ``FastFeatureBundling`` dataset.cpp:138-210):
    bundle sufficiently sparse features; try natural and shuffled orders and
    keep whichever yields fewer groups.  Dense features stay solo."""
    num_rows, num_features = bins.shape
    sparse_f = [f for f in range(num_features)
                if mappers[f].sparse_rate >= sparse_threshold
                and mappers[f].num_bin > 1]
    dense_f = [f for f in range(num_features) if f not in set(sparse_f)]
    if len(sparse_f) < 2:
        return [[f] for f in range(num_features)]
    sample = bins if num_rows <= 50000 else bins[
        np.random.RandomState(seed).choice(num_rows, 50000, replace=False)]
    nz = [np.nonzero(sample[:, f] != mappers[f].default_bin)[0] for f in sparse_f]
    g1 = find_feature_groups(nz, len(sample), max_conflict_rate)
    rng = np.random.RandomState(seed)
    g2 = find_feature_groups(nz, len(sample), max_conflict_rate,
                             rng.permutation(len(sparse_f)))
    best = g1 if len(g1) <= len(g2) else g2
    groups = [[sparse_f[i] for i in grp] for grp in best]
    # cap total bins per bundle
    capped: List[List[int]] = []
    for grp in groups:
        cur: List[int] = []
        cur_bins = 0
        for f in grp:
            nb = mappers[f].num_bin
            if cur and cur_bins + nb > max_group_bins:
                capped.append(cur)
                cur, cur_bins = [], 0
            cur.append(f)
            cur_bins += nb
        if cur:
            capped.append(cur)
    capped.extend([[f] for f in dense_f])
    return capped


def find_mappers_from_sample(sample: np.ndarray, config: Config,
                             cat_set) -> List[BinMapper]:
    """Quantile bin mappers from a sampled row block ``[S, F]``
    (reference FindBin over sampled values, `bin.cpp:72-206`; the
    sampling contract drops zeros for numerical features)."""
    mappers: List[BinMapper] = []
    for f in range(sample.shape[1]):
        m = BinMapper()
        col = sample[:, f].astype(np.float64)
        bin_type = BIN_CATEGORICAL if f in cat_set else BIN_NUMERICAL
        if bin_type == BIN_NUMERICAL:
            nz = col[(col != 0.0) | np.isnan(col)]
            m.find_bin(nz, len(col), config.max_bin,
                       config.min_data_in_bin, bin_type=bin_type,
                       use_missing=config.use_missing,
                       zero_as_missing=config.zero_as_missing)
        else:
            m.find_bin(col[~np.isnan(col)], len(col), config.max_bin,
                       config.min_data_in_bin, bin_type=bin_type,
                       use_missing=config.use_missing,
                       zero_as_missing=config.zero_as_missing)
        mappers.append(m)
    return mappers


@dataclass
class BundleInfo:
    """EFB group layout (our own encoding, replacing the reference's
    FeatureGroup bin-offset bookkeeping, `feature_group.h:30-75`).

    A stored column holds one *group*.  Singleton groups store the
    feature's bins unchanged (``feat_offset == -1``).  A multi-feature
    group column encodes: 0 = every member at its default bin; else the
    single non-default member ``f`` with bin ``b`` as
    ``off_f + b - (1 if b > default_f else 0)`` — each member owns the
    disjoint range ``[off_f, off_f + num_bin_f - 2]`` and the shared bin 0
    replaces its default (bin 0 reserved for defaults, the
    `feature_group.h:35-36` convention).  Conflicting rows (two members
    non-default; bounded by ``max_conflict_rate``) keep the last member's
    value, like the reference's push-order overwrite.
    """
    groups: List[List[int]]        # logical used-feature ids per group
    feat_group: np.ndarray         # int32 [F] group column per feature
    feat_offset: np.ndarray        # int32 [F] offset in group (-1: identity)
    group_num_bins: np.ndarray     # int32 [G]

    @property
    def is_bundled(self) -> bool:
        return bool((self.feat_offset >= 0).any())


def build_bundle_info(groups: List[List[int]],
                      num_bins: np.ndarray) -> BundleInfo:
    F = int(num_bins.shape[0])
    feat_group = np.zeros(F, np.int32)
    feat_offset = np.full(F, -1, np.int32)
    gnb = np.zeros(len(groups), np.int32)
    for g, members in enumerate(groups):
        if len(members) == 1:
            f = members[0]
            feat_group[f] = g
            gnb[g] = num_bins[f]
            continue
        off = 1
        for f in members:
            feat_group[f] = g
            feat_offset[f] = off
            off += int(num_bins[f]) - 1
        gnb[g] = off
    return BundleInfo(groups=groups, feat_group=feat_group,
                      feat_offset=feat_offset, group_num_bins=gnb)


def pack_group_columns(cols: List[np.ndarray], info: "FeatureInfo",
                       bundle: BundleInfo) -> np.ndarray:
    """Encode per-feature bin columns into group columns (the EFB
    push path, reference ``FeatureGroup::PushData``)."""
    n = len(cols[0])
    G = len(bundle.groups)
    dtype = np.uint8 if bundle.group_num_bins.max() <= 256 else np.int32
    out = np.zeros((n, G), dtype=dtype)
    for g, members in enumerate(bundle.groups):
        if len(members) == 1:
            out[:, g] = cols[members[0]].astype(dtype)
            continue
        col = np.zeros(n, np.int32)
        for f in members:
            b = cols[f].astype(np.int32)
            db = int(info.default_bins[f])
            off = int(bundle.feat_offset[f])
            nz = b != db
            enc = off + b - (b > db)
            col[nz] = enc[nz]
        out[:, g] = col.astype(dtype)
    return out


# ---------------------------------------------------------------------------
@dataclass
class FeatureInfo:
    """Static per-column metadata shipped to the device as plain arrays."""
    num_bins: np.ndarray          # int32 [F] bins per feature (incl. NaN bin)
    bin_offsets: np.ndarray       # int32 [F+1] prefix sum of num_bins
    default_bins: np.ndarray      # int32 [F]
    missing_types: np.ndarray     # int32 [F]
    is_categorical: np.ndarray    # bool  [F]

    @property
    def total_bins(self) -> int:
        return int(self.bin_offsets[-1])

    @property
    def max_num_bins(self) -> int:
        return int(self.num_bins.max()) if len(self.num_bins) else 1


class BinnedDataset:
    """The constructed training dataset (reference Dataset, dataset.h:280-578).

    Host-side numpy; pushed to device by the learner.  ``used_features``
    maps stored columns back to original feature indices (mirroring the
    reference's used_feature_map in `dataset.h`) so model output refers to
    the caller's column numbering.
    """

    def __init__(self) -> None:
        self.bins: np.ndarray = np.zeros((0, 0), dtype=np.uint8)  # [n, G]
        self.mappers: List[BinMapper] = []          # per original feature
        self.feature_info: Optional[FeatureInfo] = None
        self.bundle: Optional[BundleInfo] = None    # EFB layout (None: 1:1)
        self.metadata = Metadata()
        self.num_total_features: int = 0
        self.used_features: List[int] = []          # original idx per used column
        self.feature_names: List[str] = []
        self.config: Optional[Config] = None

    # -- construction ---------------------------------------------------
    @classmethod
    def from_raw(cls, X: np.ndarray, config: Config,
                 categorical_features: Sequence[int] = (),
                 feature_names: Optional[Sequence[str]] = None,
                 reference: Optional["BinnedDataset"] = None,
                 metadata: Optional[Metadata] = None,
                 prediction_mode: bool = False,
                 mappers: Optional[List[BinMapper]] = None,
                 bundle_allgather=None, rank: int = 0) -> "BinnedDataset":
        """Sample→FindBin→bin all rows (reference DatasetLoader::LoadFromFile
        stages, dataset_loader.cpp:159-219 + 744-993)."""
        X = np.asarray(X)
        if X.dtype == np.object_:
            X = X.astype(np.float64)
        n, num_features = X.shape
        ds = cls()
        ds.config = config
        ds.num_total_features = num_features
        ds.feature_names = (list(feature_names) if feature_names
                            else [f"Column_{i}" for i in range(num_features)])
        cat_set = set(int(c) for c in categorical_features)

        if reference is not None:
            # align bin mappers with reference dataset (used for valid sets;
            # reference LoadFromFileAlignWithOtherDataset dataset_loader.cpp:221)
            if num_features != reference.num_total_features:
                raise ValueError(
                    f"validation data has {num_features} features, train data "
                    f"has {reference.num_total_features}")
            ds.mappers = reference.mappers
            ds.used_features = reference.used_features
            ds.feature_info = reference.feature_info
            ds.feature_names = reference.feature_names
            # prediction mode: unbundled columns + sentinel categorical
            # miss bins (raw-value CategoricalDecision semantics)
            ds.bundle = None if prediction_mode else reference.bundle
            cols = []
            for f in ds.used_features:
                cols.append(ds.mappers[f].value_to_bin(
                    X[:, f], prediction_mode=prediction_mode))
            if ds.bundle is not None and ds.bundle.is_bundled:
                ds.bins = pack_group_columns(cols, ds.feature_info, ds.bundle)
            else:
                # prediction mode's categorical miss sentinel is num_bin,
                # which overflows uint8 when num_bin == 256
                force_wide = (prediction_mode
                              and ds.feature_info.max_num_bins >= 256)
                ds.bins = cls._pack_columns(cols, ds.feature_info,
                                            force_int32=force_wide)
            ds.metadata = metadata or Metadata()
            return ds

        # 1-2. sample + find bins per feature (skipped when precomputed
        # mappers are supplied — the distributed bin-finding path,
        # io/distributed.py)
        if mappers is not None:
            if len(mappers) != num_features:
                raise ValueError(
                    f"got {len(mappers)} mappers for {num_features} features")
            ds.mappers = mappers
            ds.used_features = [f for f in range(num_features)
                                if not mappers[f].is_trivial]
            # EFB with distributed ingest (VERDICT r2 #6): conflict rates
            # are rank-LOCAL, so rank 0's group proposal is broadcast
            # through the ingest collective and applied by every rank —
            # identical layouts, so data-parallel histogram collectives
            # sum matching columns.  Without a collective, bundling
            # stays off (different layouts would corrupt the psum).
            return cls._finish_from_mappers(ds, X, config, metadata, n,
                                            num_features,
                                            allow_bundle=(
                                                bundle_allgather is not None),
                                            bundle_allgather=bundle_allgather,
                                            rank=rank)
        sample_cnt = min(n, config.bin_construct_sample_cnt)
        rng = np.random.RandomState(config.data_random_seed)
        sample_idx = (np.arange(n) if sample_cnt >= n
                      else np.sort(rng.choice(n, sample_cnt, replace=False)))
        ds.mappers = find_mappers_from_sample(X[sample_idx], config, cat_set)
        ds.used_features = [f for f in range(num_features)
                            if not ds.mappers[f].is_trivial]
        return cls._finish_from_mappers(ds, X, config, metadata, n,
                                        num_features)

    @classmethod
    def _finish_from_mappers(cls, ds: "BinnedDataset", X: np.ndarray,
                             config: Config, metadata: Optional[Metadata],
                             n: int, num_features: int,
                             allow_bundle: bool = True,
                             bundle_allgather=None,
                             rank: int = 0,
                             cols: Optional[List[np.ndarray]] = None,
                             packed: Optional[np.ndarray] = None
                             ) -> "BinnedDataset":
        """Steps 3-4 of construction: bin all rows through ``ds.mappers``,
        apply EFB, pack columns (shared by the local and distributed
        bin-finding paths).  With ``bundle_allgather``, rank 0's group
        proposal is broadcast so every rank bundles identically (the
        mod-rank row shuffle makes rank 0's conflict estimate unbiased).
        ``cols`` supplies PRE-binned per-used-feature columns (the
        two-round loader bins chunk-by-chunk and never holds raw X —
        its ``X`` argument is then an empty placeholder)."""
        mappers = ds.mappers
        if not ds.used_features:
            log_warning("all features are trivial (constant); nothing to train on")
        # 3. bin every row (vectorized per column)
        if cols is None:
            cols = [mappers[f].value_to_bin(X[:, f])
                    for f in ds.used_features]
        ds.feature_info = cls._build_feature_info(
            [mappers[f] for f in ds.used_features])
        # 4. EFB: bundle sufficiently sparse features into shared columns
        #    (reference FastFeatureBundling, dataset.cpp:138-210)
        ds.bundle = None
        used_mappers = [mappers[f] for f in ds.used_features]
        # (feature-parallel composes since r4: each shard gathers its
        # features' group columns — reference bundles identically on
        # every rank for all learner types, dataset.cpp:138-210)
        if (allow_bundle and config.enable_bundle
                and len(ds.used_features) >= 2):
            n_sparse = sum(m.sparse_rate >= config.sparse_threshold
                           and m.num_bin > 1 for m in used_mappers)
            if n_sparse >= 2:
                if bundle_allgather is None or rank == 0:
                    feat_matrix = cls._pack_columns(cols, ds.feature_info)
                    groups = fast_feature_bundling(
                        feat_matrix, used_mappers, config.max_conflict_rate,
                        config.data_random_seed, config.sparse_threshold,
                        max_group_bins=256)
                else:
                    groups = None      # rank 0's proposal arrives below
                if bundle_allgather is not None:
                    # every eligible rank reaches this collective (the
                    # gates above depend only on the shared mappers)
                    proposals = bundle_allgather(
                        [[int(f) for f in grp] for grp in groups]
                        if groups is not None else None)
                    groups = [[int(f) for f in grp] for grp in proposals[0]]
                if len(groups) < len(ds.used_features):
                    ds.bundle = build_bundle_info(
                        groups, ds.feature_info.num_bins)
        if ds.bundle is not None and ds.bundle.is_bundled:
            ds.bins = pack_group_columns(cols, ds.feature_info, ds.bundle)
            log_info(f"EFB bundled {len(ds.used_features)} features into "
                     f"{ds.bins.shape[1]} groups")
        else:
            ds.bundle = None
            # `packed` (two-round loader): cols are views of an already
            # correctly-packed matrix — adopt it, don't copy
            ds.bins = (packed if packed is not None
                       else cls._pack_columns(cols, ds.feature_info))
        ds.metadata = metadata or Metadata()
        log_info(f"constructed dataset: {n} rows, "
                 f"{len(ds.used_features)}/{num_features} used features, "
                 f"{ds.feature_info.total_bins} total bins")
        return ds

    @staticmethod
    def _build_feature_info(mappers: Sequence[BinMapper]) -> FeatureInfo:
        num_bins = np.asarray([m.num_bin for m in mappers], dtype=np.int32)
        offsets = np.concatenate([np.zeros(1, np.int32),
                                  np.cumsum(num_bins, dtype=np.int32)])
        return FeatureInfo(
            num_bins=num_bins,
            bin_offsets=offsets,
            default_bins=np.asarray([m.default_bin for m in mappers], np.int32),
            missing_types=np.asarray([m.missing_type for m in mappers], np.int32),
            is_categorical=np.asarray(
                [m.bin_type == BIN_CATEGORICAL for m in mappers], bool),
        )

    @staticmethod
    def _pack_columns(cols: List[np.ndarray], info: FeatureInfo,
                      force_int32: bool = False) -> np.ndarray:
        if not cols:
            return np.zeros((0, 0), dtype=np.uint8)
        dtype = (np.int32 if force_int32 or info.max_num_bins > 256
                 else np.uint8)
        out = np.empty((len(cols[0]), len(cols)), dtype=dtype)
        for j, c in enumerate(cols):
            out[:, j] = c.astype(dtype)
        return out

    # -- views / accessors ----------------------------------------------
    @property
    def num_data(self) -> int:
        return self.bins.shape[0]

    @property
    def num_features(self) -> int:
        return self.bins.shape[1]

    def create_valid(self, X: np.ndarray, metadata: Optional[Metadata] = None,
                     prediction_mode: bool = False) -> "BinnedDataset":
        """Bin a validation matrix with THIS dataset's mappers
        (reference Dataset::CreateValid, dataset.h:398).

        ``prediction_mode`` produces an unbundled matrix with sentinel
        categorical miss bins — use for predict paths, not valid-set
        training eval."""
        return BinnedDataset.from_raw(np.asarray(X), self.config,
                                      reference=self, metadata=metadata,
                                      prediction_mode=prediction_mode)

    def subset(self, used_indices: np.ndarray) -> "BinnedDataset":
        """Row subset copy (reference CopySubset dataset.h:375)."""
        used_indices = np.asarray(used_indices, dtype=np.int64)
        out = BinnedDataset()
        out.bins = self.bins[used_indices]
        out.mappers = self.mappers
        out.feature_info = self.feature_info
        out.bundle = self.bundle
        out.num_total_features = self.num_total_features
        out.used_features = self.used_features
        out.feature_names = self.feature_names
        out.config = self.config
        md = Metadata()
        if self.metadata.label is not None:
            md.label = self.metadata.label[used_indices]
        if self.metadata.weight is not None:
            md.weight = self.metadata.weight[used_indices]
        if self.metadata.init_score is not None:
            md.init_score = self.metadata.init_score[used_indices]
        if self.metadata.query_boundaries is not None:
            md.query_boundaries = _subset_query_boundaries(
                self.metadata.query_boundaries, used_indices)
        out.metadata = md
        return out

    # -- binary serialization (reference SaveBinaryFile dataset.h:394) ---
    def save_binary(self, path: str) -> None:
        meta = {
            "version": 1,
            "num_total_features": self.num_total_features,
            "used_features": self.used_features,
            "feature_names": self.feature_names,
            "mappers": [m.to_dict() for m in self.mappers],
            "groups": (self.bundle.groups if self.bundle is not None
                       else None),
        }
        np.savez_compressed(
            path, header=json.dumps(meta).encode(),
            bins=self.bins,
            label=self.metadata.label if self.metadata.label is not None else np.zeros(0, np.float32),
            weight=self.metadata.weight if self.metadata.weight is not None else np.zeros(0, np.float32),
            query=self.metadata.query_boundaries if self.metadata.query_boundaries is not None else np.zeros(0, np.int32),
            init_score=self.metadata.init_score if self.metadata.init_score is not None else np.zeros(0, np.float64),
        )

    @classmethod
    def load_binary(cls, path: str) -> "BinnedDataset":
        z = np.load(path if path.endswith(".npz") else path + ".npz",
                    allow_pickle=False)
        meta = json.loads(bytes(z["header"]).decode())
        ds = cls()
        ds.num_total_features = meta["num_total_features"]
        ds.used_features = list(meta["used_features"])
        ds.feature_names = list(meta["feature_names"])
        ds.mappers = [BinMapper.from_dict(d) for d in meta["mappers"]]
        ds.feature_info = cls._build_feature_info(
            [ds.mappers[f] for f in ds.used_features])
        if meta.get("groups"):
            ds.bundle = build_bundle_info(
                [list(g) for g in meta["groups"]], ds.feature_info.num_bins)
        ds.bins = z["bins"]
        md = Metadata()
        if len(z["label"]):
            md.label = z["label"]
        if len(z["weight"]):
            md.weight = z["weight"]
        if len(z["query"]):
            md.query_boundaries = z["query"]
        if len(z["init_score"]):
            md.init_score = z["init_score"]
        ds.metadata = md
        return ds
