"""Feature binning (quantization) — TPU-native BinMapper.

Behavioral parity with the reference's ``BinMapper``
(`/root/reference/include/LightGBM/bin.h:89-215`, `src/io/bin.cpp:72-330`):
greedy bin-boundary search over sampled distinct values
(``GreedyFindBin`` `bin.cpp:72-149`), zero-as-one-bin handling
(``FindBinWithZeroAsOneBin`` `bin.cpp:151-206`), missing-value types
None/Zero/NaN (`bin.h:20-24`), and count-sorted categorical mapping
(`bin.cpp:300-330`).

Binning runs once at ingest on the host (numpy); the result feeds the
HBM-resident binned matrix (`lightgbm_tpu.io.dataset`).  Unlike the
reference there are no per-storage-format Bin subclasses (dense/sparse/
4-bit/ordered): on TPU a single dense int column store is the fast path,
so ``value_to_bin`` is vectorized over whole columns.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

K_ZERO_THRESHOLD = 1e-35          # reference bin.h kZeroThreshold
_K_SPARSE_THRESHOLD = 0.8

# MissingType (reference bin.h:20-24)
MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

# BinType (reference bin.h)
BIN_NUMERICAL = 0
BIN_CATEGORICAL = 1


def _double_upper_bound(x: float) -> float:
    """Next representable float32-safe upper bound (reference uses
    ``Common::GetDoubleUpperBound`` = std::nextafter towards +inf)."""
    return float(np.nextafter(np.float64(x), np.float64(np.inf)))


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                    max_bin: int, total_cnt: int,
                    min_data_in_bin: int) -> List[float]:
    """Greedy bin boundary search (reference bin.cpp:72-149).

    Returns upper bounds; last is +inf.  When there are few distinct values
    each gets its own bin (subject to min_data_in_bin); otherwise boundaries
    are placed to even out per-bin counts, with over-represented single
    values ("big" values) pinned to their own bins.
    """
    num_distinct = len(distinct_values)
    assert max_bin > 0
    bin_upper_bound: List[float] = []
    if num_distinct <= max_bin:
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += int(counts[i])
            if cur_cnt >= min_data_in_bin:
                val = _double_upper_bound((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bin_upper_bound or val > bin_upper_bound[-1]:
                    bin_upper_bound.append(val)
                    cur_cnt = 0
        bin_upper_bound.append(math.inf)
        return bin_upper_bound

    if min_data_in_bin > 0:
        max_bin = min(max_bin, max(1, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    rest_bin_cnt = max_bin
    rest_sample_cnt = total_cnt
    is_big = counts >= mean_bin_size
    rest_bin_cnt -= int(is_big.sum())
    rest_sample_cnt -= int(counts[is_big].sum())
    if rest_bin_cnt > 0:
        mean_bin_size = rest_sample_cnt / rest_bin_cnt

    upper_bounds = [math.inf] * max_bin
    lower_bounds = [math.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = float(distinct_values[0])
    cur_cnt = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur_cnt += int(counts[i])
        if (is_big[i] or cur_cnt >= mean_bin_size or
                (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = float(distinct_values[i])
            bin_cnt += 1
            lower_bounds[bin_cnt] = float(distinct_values[i + 1])
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                # C++ double division tolerates rest_bin_cnt==0 (yields inf)
                mean_bin_size = (rest_sample_cnt / rest_bin_cnt
                                 if rest_bin_cnt > 0 else math.inf)
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _double_upper_bound((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
        if not bin_upper_bound or val > bin_upper_bound[-1]:
            bin_upper_bound.append(val)
    bin_upper_bound.append(math.inf)
    return bin_upper_bound


def find_bin_with_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                                  max_bin: int, total_sample_cnt: int,
                                  min_data_in_bin: int) -> List[float]:
    """Reserve a dedicated bin straddling zero (reference bin.cpp:151-206)."""
    dv = np.asarray(distinct_values, dtype=np.float64)
    cnts = np.asarray(counts, dtype=np.int64)
    left_mask = dv <= -K_ZERO_THRESHOLD
    right_mask = dv > K_ZERO_THRESHOLD
    zero_mask = ~left_mask & ~right_mask
    left_cnt_data = int(cnts[left_mask].sum())
    cnt_zero = int(cnts[zero_mask].sum())
    right_cnt_data = int(cnts[right_mask].sum())

    left_idx = np.nonzero(~left_mask)[0]
    left_cnt = int(left_idx[0]) if len(left_idx) else len(dv)

    bin_upper_bound: List[float] = []
    if left_cnt > 0:
        denom = max(1, total_sample_cnt - cnt_zero)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bin_upper_bound = greedy_find_bin(dv[:left_cnt], cnts[:left_cnt],
                                          left_max_bin, left_cnt_data, min_data_in_bin)
        bin_upper_bound[-1] = -K_ZERO_THRESHOLD

    right_idx = np.nonzero(right_mask[left_cnt:])[0]
    right_start = left_cnt + int(right_idx[0]) if len(right_idx) else -1

    if right_start >= 0:
        right_max_bin = max_bin - 1 - len(bin_upper_bound)
        assert right_max_bin > 0
        right_bounds = greedy_find_bin(dv[right_start:], cnts[right_start:],
                                       right_max_bin, right_cnt_data, min_data_in_bin)
        bin_upper_bound.append(K_ZERO_THRESHOLD)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(math.inf)
    return bin_upper_bound


class BinMapper:
    """Per-feature value→bin mapping (reference bin.h:89-215)."""

    def __init__(self) -> None:
        self.num_bin: int = 1
        self.missing_type: int = MISSING_NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 0.0
        self.bin_type: int = BIN_NUMERICAL
        self.bin_upper_bound: np.ndarray = np.array([math.inf])
        self.categorical_2_bin: dict = {}
        self.bin_2_categorical: List[int] = []
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0

    # ------------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 0,
                 bin_type: int = BIN_NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False) -> None:
        """Compute bin boundaries from sampled values (reference bin.cpp:208-330).

        ``values`` are the sampled *non-zero* values (zeros are implied by
        ``total_sample_cnt - len(values)``, matching the reference's sparse
        sampling contract).
        """
        from ..obs import span
        with span("io.find_bin"):
            self._find_bin(values, total_sample_cnt, max_bin,
                           min_data_in_bin, min_split_data, bin_type,
                           use_missing, zero_as_missing)

    def _find_bin(self, values, total_sample_cnt, max_bin, min_data_in_bin,
                  min_split_data, bin_type, use_missing,
                  zero_as_missing) -> None:
        values = np.asarray(values, dtype=np.float64)
        nan_mask = np.isnan(values)
        na_cnt = int(nan_mask.sum())
        values = values[~nan_mask]

        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            self.missing_type = MISSING_NAN if na_cnt > 0 else MISSING_NONE

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - len(values) - na_cnt)

        # distinct values with zero spliced at its sorted position
        values = np.sort(values)
        distinct_values: List[float] = []
        counts: List[int] = []
        if len(values) == 0 or (values[0] > 0.0 and zero_cnt > 0):
            distinct_values.append(0.0)
            counts.append(zero_cnt)
        prev = None
        for v in values:
            if prev is None or v > prev:
                if prev is not None and prev < 0.0 and v > 0.0:
                    distinct_values.append(0.0)
                    counts.append(zero_cnt)
                distinct_values.append(float(v))
                counts.append(1)
            else:
                distinct_values[-1] = float(v)
                counts[-1] += 1
            prev = v
        if len(values) > 0 and values[-1] < 0.0 and zero_cnt > 0:
            distinct_values.append(0.0)
            counts.append(zero_cnt)

        dv = np.asarray(distinct_values)
        cnts = np.asarray(counts, dtype=np.int64)
        if len(dv) == 0:
            dv = np.array([0.0])
            cnts = np.array([max(0, zero_cnt)], dtype=np.int64)
        self.min_val = float(dv[0])
        self.max_val = float(dv[-1])
        num_distinct = len(dv)

        if bin_type == BIN_NUMERICAL:
            if self.missing_type == MISSING_ZERO:
                bub = find_bin_with_zero_as_one_bin(dv, cnts, max_bin,
                                                    total_sample_cnt, min_data_in_bin)
                if len(bub) == 2:
                    self.missing_type = MISSING_NONE
            elif self.missing_type == MISSING_NONE:
                bub = find_bin_with_zero_as_one_bin(dv, cnts, max_bin,
                                                    total_sample_cnt, min_data_in_bin)
            else:
                bub = find_bin_with_zero_as_one_bin(dv, cnts, max_bin - 1,
                                                    total_sample_cnt - na_cnt,
                                                    min_data_in_bin)
                bub.append(math.nan)     # last bin reserved for NaN
            self.bin_upper_bound = np.asarray(bub, dtype=np.float64)
            self.num_bin = len(bub)
            # default bin = bin containing 0.0
            finite = self.bin_upper_bound.copy()
            if self.missing_type == MISSING_NAN:
                finite = finite[:-1]
            self.default_bin = int(np.searchsorted(finite, 0.0, side="left"))
            cnt_in_bin = self._count_in_bin(dv, cnts, na_cnt)
        else:
            # categorical: non-negative ints, sorted by count desc (bin.cpp:300-330)
            ints = dv.astype(np.int64)
            neg = ints < 0
            na_cnt += int(cnts[neg].sum())
            ints, cnts2 = ints[~neg], cnts[~neg]
            # merge duplicate ints (possible after float->int cast)
            uniq, inv = np.unique(ints, return_inverse=True)
            merged = np.zeros(len(uniq), dtype=np.int64)
            np.add.at(merged, inv, cnts2)
            order = np.argsort(-merged, kind="stable")
            cats = uniq[order]
            ccnt = merged[order]
            # avoid category 0 landing in bin 0 (reference bin.cpp:321-329)
            if len(cats) and cats[0] == 0:
                if len(cats) == 1:
                    cats = np.append(cats, cats[0] + 1)
                    ccnt = np.append(ccnt, 0)
                cats[[0, 1]] = cats[[1, 0]]
                ccnt[[0, 1]] = ccnt[[1, 0]]
            # drop rare categories: keep 99% of data (reference cut_cnt logic)
            if len(cats) == 0:
                cats = np.array([0], dtype=np.int64)
                ccnt = np.array([max(0, total_sample_cnt - na_cnt)], dtype=np.int64)
            cut = int(0.99 * (total_sample_cnt - na_cnt))
            keep = 0
            acc = 0
            for i in range(len(cats)):
                if acc >= cut or keep >= max_bin:
                    break
                acc += int(ccnt[i])
                keep += 1
            keep = max(1, keep)
            cats, ccnt = cats[:keep], ccnt[:keep]
            self.bin_2_categorical = [int(c) for c in cats]
            self.categorical_2_bin = {int(c): i for i, c in enumerate(cats)}
            self.num_bin = len(cats)
            self.missing_type = MISSING_NAN if na_cnt > 0 else MISSING_NONE
            self.default_bin = int(self.categorical_2_bin.get(0, 0))
            cnt_in_bin = ccnt

        # trivial / sparse-rate bookkeeping (bin.cpp tail of FindBin)
        self.is_trivial = self.num_bin <= 1
        if total_sample_cnt > 0 and len(cnt_in_bin) > self.default_bin:
            self.sparse_rate = float(cnt_in_bin[self.default_bin]) / total_sample_cnt
        else:
            self.sparse_rate = 0.0

    def _count_in_bin(self, dv: np.ndarray, cnts: np.ndarray, na_cnt: int) -> np.ndarray:
        cnt_in_bin = np.zeros(self.num_bin, dtype=np.int64)
        finite_bounds = self.bin_upper_bound
        if self.missing_type == MISSING_NAN:
            finite_bounds = finite_bounds[:-1]
        idx = np.searchsorted(finite_bounds, dv, side="left")
        idx = np.minimum(idx, self.num_bin - 1)
        np.add.at(cnt_in_bin, idx, cnts)
        if self.missing_type == MISSING_NAN:
            cnt_in_bin[self.num_bin - 1] = na_cnt
        return cnt_in_bin

    # ------------------------------------------------------------------
    def value_to_bin(self, values: np.ndarray,
                     prediction_mode: bool = False) -> np.ndarray:
        """Vectorized value→bin (reference bin.h:450-486 binary search).

        ``prediction_mode`` affects categorical features only: unseen /
        negative / NaN categories map to the sentinel bin ``num_bin``
        (beyond every split mask, so they go RIGHT — the reference's
        raw-value ``CategoricalDecision`` semantics, `tree.h:252-271`)
        instead of the train-binning miss bin ``num_bin - 1``
        (`bin.h:470-485`).
        """
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_CATEGORICAL:
            ints = np.where(np.isnan(values), -1, values).astype(np.int64)
            cats = np.asarray(self.bin_2_categorical, dtype=np.int64)
            sorter = np.argsort(cats)
            pos = np.searchsorted(cats[sorter], ints)
            pos = np.clip(pos, 0, len(cats) - 1)
            hit = cats[sorter[pos]] == ints
            miss_bin = self.num_bin if prediction_mode else self.num_bin - 1
            out = np.where(hit, sorter[pos], miss_bin).astype(np.int32)
            return out

        nan_mask = np.isnan(values)
        if self.missing_type != MISSING_NAN:
            # reference ValueToBin converts NaN to 0.0 when the feature has no
            # NaN bin (MissingType None/Zero)
            values = np.where(nan_mask, 0.0, values)
            nan_mask = np.zeros_like(nan_mask)
        finite_bounds = self.bin_upper_bound
        if self.missing_type == MISSING_NAN:
            finite_bounds = finite_bounds[:-1]
        # bin = first i with value <= upper_bound[i]
        out = np.searchsorted(finite_bounds, values, side="left").astype(np.int32)
        out = np.minimum(out, self.num_bin - 1)
        if self.missing_type == MISSING_NAN:
            out[nan_mask] = self.num_bin - 1
        return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative value for a bin (reference bin.h:107-113)."""
        if self.bin_type == BIN_CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx])
        if bin_idx >= len(self.bin_upper_bound):
            return self.max_val
        return float(self.bin_upper_bound[bin_idx])

    def threshold_value(self, threshold_bin: int) -> float:
        """Real-valued split threshold for model serialization: the bin upper
        bound (left subtree takes value <= threshold)."""
        ub = self.bin_upper_bound
        if self.missing_type == MISSING_NAN:
            ub = ub[:-1]
        t = min(threshold_bin, len(ub) - 1)
        v = float(ub[t])
        return v

    # serialization ----------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "bin_upper_bound": [float(v) for v in self.bin_upper_bound],
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = int(d["bin_type"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(c) for c in d["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        return m
