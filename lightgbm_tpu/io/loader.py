"""Text dataset loading: CSV / TSV / LibSVM with side files.

Counterpart of the reference ``DatasetLoader`` + ``Parser``
(`/root/reference/src/io/dataset_loader.cpp:159-219`, `src/io/parser.cpp`):
format auto-detection, ``label_column``/``ignore_column``/
``categorical_column`` handling (index ``N`` or ``name:xx`` syntax,
`config.h` IOConfig docs), side files ``.weight``/``.query``/``.init``
(`src/io/metadata.cpp` load paths), and distributed row sharding
(pre-partition or ``i % num_machines``, `dataset_loader.cpp:639-742`).

The inner parse runs through numpy (a C++ fast parser is the planned
native replacement; the format contract lives here).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..utils.file_io import localize
from ..utils.log import log_info, log_warning
from .dataset import BinnedDataset, Metadata


def detect_format(path: str, has_header: bool) -> str:
    """CSV vs TSV vs LibSVM auto-detection (reference Parser::CreateParser,
    src/io/parser.cpp format sniffing)."""
    with open(path) as f:
        lines = []
        for _ in range(32):
            ln = f.readline()
            if not ln:
                break
            lines.append(ln.rstrip("\n"))
    if has_header and lines:
        lines = lines[1:]
    if not lines:
        return "csv"
    sample = lines[0]
    if ":" in sample.split(",")[0].split("\t")[0].split(" ")[-1] \
            and any(":" in tok for tok in sample.split()[1:2]):
        return "libsvm"
    n_tab = sample.count("\t")
    n_comma = sample.count(",")
    if any(":" in tok for tok in sample.split()[1:]):
        return "libsvm"
    if n_tab >= n_comma and n_tab > 0:
        return "tsv"
    if n_comma > 0:
        return "csv"
    if " " in sample:
        return "libsvm" if ":" in sample else "tsv"
    return "csv"


def _parse_column_spec(spec: str, header_names: Optional[List[str]]) -> int:
    """Column spec: integer index or ``name:colname``."""
    if spec.startswith("name:"):
        name = spec[5:]
        if not header_names:
            raise ValueError(f"column {spec!r} needs a header")
        return header_names.index(name)
    return int(spec)


def _parse_multi_spec(spec: str, header_names) -> List[int]:
    if not spec:
        return []
    if spec.startswith("name:"):
        names = spec[5:].split(",")
        return [header_names.index(n) for n in names]
    return [int(s) for s in spec.replace(";", ",").split(",") if s != ""]


def parse_file(path: str, config: Config
               ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray],
                          Optional[np.ndarray], List[str], List[int]]:
    """-> (X, label, weight, query, feature_names, categorical_cols)."""
    from ..obs import span
    with span("io.parse_file", path=os.path.basename(path)):
        return _parse_file(path, config)


def _parse_file(path: str, config: Config):
    from ..utils.faults import fault_point
    from ..utils.retry import retry_call

    def _localize(p):
        # named injection seam + retried remote fetch: a flaky remote
        # filesystem read (the fork's HDFS shard download analog) is a
        # transient, not a lost training run
        fault_point("loader.read")
        return localize(p)

    path = retry_call(_localize, path,    # remote schemes -> temp copy
                      what="loader.read")
    fmt = detect_format(path, config.has_header)
    header_names: Optional[List[str]] = None
    skip = 0
    if config.has_header:
        with open(path) as f:
            first = f.readline().rstrip("\n")
        sep = {"csv": ",", "tsv": "\t", "libsvm": " "}[fmt]
        header_names = first.split(sep)
        skip = 1

    weight_inline = None
    query_inline = None
    if fmt == "libsvm":
        from .. import native
        got = native.parse_libsvm(path, skip)
        if got is not None:
            X, label = got
        else:
            X, label = _parse_libsvm(path, skip)
        feature_names = [f"Column_{i}" for i in range(X.shape[1])]
        cat_cols: List[int] = []
    else:
        sep = "," if fmt == "csv" else "\t"
        from .. import native
        raw = native.parse_delimited(path, sep, skip)
        if raw is None:
            raw = np.genfromtxt(path, delimiter=sep, skip_header=skip,
                                dtype=np.float64)
        if raw.ndim == 1:
            raw = raw.reshape(-1, 1)
        label_idx, weight_idx, query_idx, keep, feature_names, cat_cols = \
            _column_plan(raw.shape[1], config, header_names)
        if weight_idx is not None:
            weight_inline = raw[:, weight_idx].astype(np.float32)
        if query_idx is not None:
            query_inline = raw[:, query_idx]
        label = raw[:, label_idx].astype(np.float32)
        X = raw[:, keep]
    from ..utils.file_io import release
    release(path)                       # free the localized copy now
    return X, label, weight_inline, query_inline, feature_names, cat_cols


def _parse_libsvm(path: str, skip: int) -> Tuple[np.ndarray, np.ndarray]:
    labels: List[float] = []
    rows: List[List[Tuple[int, float]]] = []
    max_idx = -1
    with open(path) as f:
        for i, line in enumerate(f):
            if i < skip:
                continue
            line = line.strip()
            if not line:
                continue
            toks = line.split()
            labels.append(float(toks[0]))
            feats = []
            for tok in toks[1:]:
                if ":" not in tok:
                    continue
                k, v = tok.split(":", 1)
                idx = int(k)
                feats.append((idx, float(v)))
                max_idx = max(max_idx, idx)
            rows.append(feats)
    X = np.zeros((len(rows), max_idx + 1), np.float64)
    for r, feats in enumerate(rows):
        for idx, v in feats:
            X[r, idx] = v
    return X, np.asarray(labels, np.float32)


def _column_plan(ncol: int, config: Config, header_names):
    """Row-independent column bookkeeping for delimited files (label /
    weight / query / ignore / categorical columns), shared by the
    in-memory and two-round paths."""
    label_idx = (_parse_column_spec(config.label_column, header_names)
                 if config.label_column else 0)
    drop = {label_idx}
    weight_idx = query_idx = None
    if config.weight_column:
        weight_idx = _parse_column_spec(config.weight_column, header_names)
        drop.add(weight_idx)
    if config.group_column:
        query_idx = _parse_column_spec(config.group_column, header_names)
        drop.add(query_idx)
    for ig in _parse_multi_spec(config.ignore_column, header_names):
        drop.add(ig)
    keep = [i for i in range(ncol) if i not in drop]
    if header_names:
        names = [header_names[i] for i in keep]
    else:
        names = [f"Column_{i}" for i in range(len(keep))]
    cat_cols = []
    if config.categorical_column:
        cat_orig = _parse_multi_spec(config.categorical_column, header_names)
        remap = {orig: j for j, orig in enumerate(keep)}
        cat_cols = [remap[c] for c in cat_orig if c in remap]
    return label_idx, weight_idx, query_idx, keep, names, cat_cols


def raw_data_row_count(path: str, skip: int) -> int:
    """Data row count via a raw byte scan (no parsing; bounded reads).
    Blank lines are NOT rows — the chunk parsers skip them, and the
    count must match or the global sample-index draw shifts (shared by
    the two-round loader and the out-of-core shard ingest,
    ``io/outofcore.py``, whose multi-file sample discipline needs every
    file's exact row count before any file is parsed)."""
    n = 0
    pending = False      # current line has non-whitespace content
    with open(path, "rb") as f:
        while True:
            chunk = f.read(4 << 20)
            if not chunk:
                break
            filtered = chunk.translate(None, delete=b"\r \t")
            arr = np.frombuffer(filtered, np.uint8)
            nls = np.flatnonzero(arr == 10)
            if len(nls):
                gaps = np.diff(np.concatenate([[-1], nls])) > 1
                if nls[0] == 0 and pending:
                    gaps[0] = True   # line continued from prior chunk
                n += int(gaps.sum())
                pending = bool(len(arr) - 1 - nls[-1] > 0)
            else:
                pending = pending or len(arr) > 0
    if pending:
        n += 1                      # unterminated final line
    return n - skip


def load_file_two_round(path: str, config: Config, rank: int = 0,
                        num_machines: int = 1,
                        allgather=None) -> "BinnedDataset":
    """Two-round low-memory ingest (reference `dataset_loader.cpp:698-742`
    + `utils/pipeline_reader.h:26+`): round 1 streams bounded chunks to
    collect the bin-finding sample (row count via a raw scan, so the
    sample indices MATCH the in-memory path's RNG draw — byte-identical
    mappers); round 2 streams again, binning each chunk straight into
    the packed column store.  Peak memory is the binned matrix plus one
    chunk — the raw float64 matrix (8 bytes/cell) never exists.

    Formats: CSV/TSV (delimited chunks) and LibSVM (chunked sparse
    parse; the native layer emits [rows, 1+F] with the label in column
    0, so the delimited machinery applies unchanged).

    Distributed (``num_machines > 1``): mod-rank row sharding composes
    by index arithmetic — this rank keeps global rows ``r ≡ rank (mod
    S)`` from the same chunk stream, the bin-finding sample is drawn
    over the LOCAL shard with the same per-rank RNG as the in-memory
    path (`find_bins_distributed`), and the sampled rows feed the same
    feature-sharded mapper allgather, so every rank bins identically
    (VERDICT r3 #9; reference `dataset_loader.cpp:639-742`).
    """
    from .. import native
    path = localize(path)
    fmt = detect_format(path, config.has_header)
    header_names = None
    skip = 1 if config.has_header else 0
    S = max(1, num_machines)
    # pre-partition: each rank already has its own file — keep every
    # row, but bin finding still runs feature-sharded across ranks
    stride = 1 if (S > 1 and config.is_pre_partition) else S

    if fmt == "libsvm":
        scanned = native.scan_libsvm(path, skip)
        if scanned is None:
            raise ValueError("native libsvm scan failed")
        n, fcols = scanned
        if S > 1:
            # every rank must bin against the same column count
            fcols = max(int(c) for c in allgather(int(fcols)))
        ncol = fcols + 1                 # + label column 0
        chunk_bytes = 4 << 20

        def chunk_stream():
            return native.parse_libsvm_chunks(path, skip, fcols,
                                              chunk_bytes=chunk_bytes)
    else:
        sep = {"csv": ",", "tsv": "\t"}[fmt]
        if config.has_header:
            with open(path) as f:
                header_names = f.readline().rstrip("\n").split(sep)

        # round 0: data row count via a raw scan (extracted to
        # raw_data_row_count so the out-of-core shard ingest shares the
        # exact same blank-line discipline)
        n = raw_data_row_count(path, skip)
        ncol = None
        chunk_bytes = 4 << 20           # bounded: ~4 MB text per chunk

        def chunk_stream():
            return native.parse_delimited_chunks(path, sep, skip,
                                                 chunk_bytes=chunk_bytes)
    if n <= 0:
        raise ValueError(f"no data rows in {path!r}")
    n_full = n
    # fail BEFORE streaming the whole file: a group column means ranking
    # queries, which mod-rank sharding would split
    if config.group_column and stride > 1:
        raise ValueError(
            "mod-rank row sharding would split ranking queries; use "
            "is_pre_partition=true with per-rank files (reference "
            "dataset_loader.cpp:639-742 contract)")

    # local shard: global rows rank, rank+stride, ... (mod-rank,
    # matching the in-memory distributed path); stride == 1 keeps all
    local_n = len(range(rank % stride if stride > 1 else 0, n, stride))
    # sample draw: global RNG single-machine (byte-identical mappers),
    # per-rank RNG over the local shard under distribution (matching
    # find_bins_distributed's own draw)
    if S == 1:
        sample_cnt = min(n, config.bin_construct_sample_cnt)
        rng = np.random.RandomState(config.data_random_seed)
        local_sample = (np.arange(n) if sample_cnt >= n
                        else np.sort(rng.choice(n, sample_cnt,
                                                replace=False)))
        sample_gidx = local_sample
    else:
        sample_cnt = min(local_n, config.bin_construct_sample_cnt)
        rng = np.random.RandomState(config.data_random_seed + rank)
        local_sample = (np.arange(local_n) if sample_cnt >= local_n
                        else np.sort(rng.choice(local_n, sample_cnt,
                                                replace=False)))
        sample_gidx = (local_sample if stride == 1
                       else rank + local_sample * stride)  # sorted affine

    # round 1: stream chunks, keep only sampled rows
    sample_rows = []
    base = 0
    plan = None
    for chunk in chunk_stream():
        if plan is None:
            plan = _column_plan(chunk.shape[1], config, header_names)
        lo = np.searchsorted(sample_gidx, base)
        hi = np.searchsorted(sample_gidx, base + len(chunk))
        if hi > lo:
            sample_rows.append(chunk[sample_gidx[lo:hi] - base])
        base += len(chunk)
    if base != n:
        raise ValueError(
            f"chunked parse saw {base} rows, raw scan counted {n}")
    label_idx, weight_idx, query_idx, keep, names, cat_cols = plan
    if query_idx is not None and stride > 1:
        raise ValueError(
            "mod-rank row sharding would split ranking queries; use "
            "is_pre_partition=true with per-rank files (reference "
            "dataset_loader.cpp:639-742 contract)")
    sample = np.concatenate(sample_rows)[:, keep]

    from .dataset import BinnedDataset, find_mappers_from_sample
    if S > 1:
        # the sampled local rows ARE find_bins_distributed's own draw
        # (same rng, len == sample_cnt -> it uses every row), so the
        # feature-sharded mapper allgather matches the in-memory
        # distributed path exactly
        from .distributed import find_bins_distributed
        mappers = find_bins_distributed(sample, config, rank, S,
                                        allgather, cat_cols)
        if len(mappers) < sample.shape[1]:
            keep = keep[:len(mappers)]
            names = names[:len(mappers)]
            cat_cols = [c for c in cat_cols if c < len(mappers)]
    else:
        mappers = find_mappers_from_sample(sample, config, set(cat_cols))
    del sample, sample_rows
    used = [f for f in range(len(keep)) if not mappers[f].is_trivial]

    # round 2: bin each chunk straight into the column store, using the
    # SAME dtype _pack_columns would choose so the matrix can be adopted
    # without a copy when EFB doesn't engage
    max_nb = max((mappers[f].num_bin for f in used), default=2)
    prebinned = np.zeros((local_n, len(used)),
                         np.uint8 if max_nb <= 256 else np.int32)
    label = np.zeros(local_n, np.float32)
    weight = np.zeros(local_n, np.float32) if weight_idx is not None else None
    query = np.zeros(local_n, np.float64) if query_idx is not None else None
    base = 0       # global row index at chunk start
    lbase = 0      # local (this-rank) rows written so far
    for chunk in chunk_stream():
        if stride > 1:
            first = (-(base - rank) % stride)     # first local row offset
            sel = np.arange(first, len(chunk), stride)
            chunk_loc = chunk[sel]
        else:
            chunk_loc = chunk
        m = len(chunk_loc)
        label[lbase:lbase + m] = chunk_loc[:, label_idx]
        if weight is not None:
            weight[lbase:lbase + m] = chunk_loc[:, weight_idx]
        if query is not None:
            query[lbase:lbase + m] = chunk_loc[:, query_idx]
        for j, f in enumerate(used):
            prebinned[lbase:lbase + m, j] = mappers[f].value_to_bin(
                chunk_loc[:, keep[f]])
        base += len(chunk)
        lbase += m
    if lbase != local_n:
        raise ValueError(
            f"sharded chunk stream yielded {lbase} rows, expected "
            f"{local_n}")
    n = local_n
    from ..utils.file_io import release
    release(path)

    md = Metadata()
    md.set_field("label", label)
    if weight is not None:
        md.set_field("weight", weight)
    if query is not None:
        change = np.nonzero(np.diff(query))[0] + 1
        boundaries = np.concatenate([[0], change, [len(query)]])
        md.query_boundaries = boundaries.astype(np.int32)

    ds = BinnedDataset()
    ds.config = config
    ds.num_total_features = len(keep)
    ds.feature_names = names
    ds.mappers = mappers
    ds.used_features = used
    cols = [prebinned[:, j] for j in range(len(used))]
    empty_X = np.zeros((n, 0))
    ds = BinnedDataset._finish_from_mappers(
        ds, empty_X, config, md, n, len(keep), cols=cols, packed=prebinned,
        allow_bundle=(S == 1 or allgather is not None),
        bundle_allgather=(allgather if S > 1 else None), rank=rank)
    ds._global_rows = n_full    # pre-shard row count (side-file slicing)
    log_info(f"two-round loading: {n} rows streamed"
             + (f" (rank {rank}/{S})" if S > 1 else "")
             + ", peak holds the binned store only")
    return ds


def load_raw_matrix(path: str, has_header: bool = False
                    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Prediction-input parse: ``-> (X, label_or_None)`` with the same
    format autodetection and label-column convention as training files
    (reference Predictor file flow, `src/application/predictor.hpp:115+`
    reuses the training Parser, so column 0 / the LibSVM label token is
    stripped from the features)."""
    cfg = Config.from_params({"has_header": has_header})
    X, label, _, _, _, _ = parse_file(path, cfg)
    return X, label


def _load_side_file(path: str, dtype=np.float32) -> Optional[np.ndarray]:
    from ..utils.file_io import release
    try:
        local = localize(path)          # one remote round-trip, not two
    except FileNotFoundError:
        return None                     # absent side file — not an error
    if not os.path.exists(local):
        return None
    try:
        return np.loadtxt(local, dtype=dtype).reshape(-1)
    finally:
        release(local)


def load_file(path: str, config: Config,
              reference: Optional[BinnedDataset] = None,
              rank: int = 0, num_machines: int = 1,
              allgather=None) -> BinnedDataset:
    """Full file->BinnedDataset pipeline (reference
    DatasetLoader::LoadFromFile, dataset_loader.cpp:159-219), incl. the
    binary-cache fast path (SaveBinaryFile/CheckCanLoadFromBin).

    With ``num_machines > 1`` and an ``allgather`` collective, bin
    finding runs distributed: feature-sharded quantiles over the local
    row shard, mappers allgathered so every rank bins identically
    (`dataset_loader.cpp:816-880`; see ``io/distributed.py``)."""
    from ..obs import span
    with span("io.load_file", path=os.path.basename(path)):
        return _load_file(path, config, reference, rank, num_machines,
                          allgather)


def _load_file(path: str, config: Config,
               reference: Optional[BinnedDataset],
               rank: int, num_machines: int, allgather) -> BinnedDataset:
    bin_path = path + ".bin.npz"
    is_local = "://" not in path
    # the cache stores whatever one process binned — single-machine,
    # local-FS only (a shard cache would hand other ranks the wrong rows,
    # and all ranks would race-write the same file)
    if (config.enable_load_from_binary_file and reference is None
            and num_machines == 1 and is_local
            and os.path.exists(bin_path)
            and os.path.getmtime(bin_path) >= os.path.getmtime(path)):
        log_info(f"loading binary cache {bin_path}")
        return BinnedDataset.load_binary(bin_path)

    # two-round / low-memory loading (use_two_round_loading): stream the
    # file in bounded chunks, never materializing the raw float matrix
    # (reference dataset_loader.cpp:698-742; HIGGS peak-RAM contract,
    # docs/Experiments.rst:156-160)
    if config.use_two_round_loading:
        if num_machines > 1 and allgather is None:
            from .distributed import external_collectives
            ext = external_collectives()
            if ext is not None:
                allgather = ext.allgather
        if reference is not None or (num_machines > 1 and allgather is None):
            log_warning("use_two_round_loading is ignored for aligned "
                        "valid sets (and distributed loading without a "
                        "collective backend); using the in-memory path")
        else:
            from .. import native
            from ..utils.file_io import release
            local = localize(path)      # ONE download; reused below
            fmt = detect_format(local, config.has_header)
            if fmt in ("csv", "tsv", "libsvm") and native.available():
                try:
                    ds = load_file_two_round(local, config, rank=rank,
                                             num_machines=num_machines,
                                             allgather=allgather)
                finally:
                    release(local)
                # side files are GLOBAL-length: under mod-rank sharding
                # they must be sliced to this rank's rows exactly like
                # the in-memory path does (review r4: attaching the full
                # array silently weighted rows by the wrong entries)
                n_full = getattr(ds, "_global_rows", ds.num_data)
                sharded = n_full != ds.num_data
                w2 = _load_side_file(path + ".weight")
                if w2 is not None:
                    if sharded:
                        w2 = w2[rank::num_machines]
                    ds.metadata.set_field("weight", w2)
                init2 = _load_side_file(path + ".init", np.float64)
                if init2 is not None:
                    if sharded:
                        # flat [n_full * K] in class-major blocks
                        K = max(1, len(init2) // n_full)
                        sel = np.arange(rank, n_full, num_machines)
                        init2 = np.concatenate(
                            [init2[k * n_full + sel] for k in range(K)])
                    ds.metadata.set_field("init_score", init2)
                q2 = _load_side_file(path + ".query", np.int64)
                if q2 is not None:
                    if sharded:
                        raise ValueError(
                            "mod-rank row sharding would split ranking "
                            "queries; use is_pre_partition=true with "
                            "per-rank files (reference "
                            "dataset_loader.cpp:639-742 contract)")
                    ds.metadata.set_field("group", q2.astype(np.int32))
                if config.is_save_binary_file and is_local:
                    ds.save_binary(bin_path[:-4])
                    log_info(f"saved binary cache {bin_path}")
                return ds
            release(local)
            log_warning("use_two_round_loading needs the native parser "
                        "and a CSV/TSV file; falling back to in-memory "
                        "loading")

    X, label, weight, query_inline, feature_names, cat_cols = \
        parse_file(path, config)

    # side files (reference metadata.cpp LoadWeights/LoadQueryBoundaries/
    # LoadInitialScore)
    w = _load_side_file(path + ".weight")
    if w is not None:
        weight = w
    init_score = _load_side_file(path + ".init", np.float64)
    q = _load_side_file(path + ".query", np.int64)

    # distributed row sharding (dataset_loader.cpp:639-742): pre-partition
    # means each rank already has its own file; otherwise mod-rank rows
    if num_machines > 1 and not config.is_pre_partition:
        if q is not None or query_inline is not None:
            raise ValueError(
                "mod-rank row sharding would split ranking queries; use "
                "is_pre_partition=true with per-rank files (reference "
                "dataset_loader.cpp:639-742 contract)")
        n_full = len(X)
        sel = np.arange(rank, n_full, num_machines)
        X, label = X[sel], label[sel]
        if weight is not None:
            weight = weight[sel]
        if init_score is not None:
            # init_score is flat [n*num_class] in class-major blocks
            # (Metadata convention): take this rank's rows per block
            K = max(1, len(init_score) // n_full)
            init_score = np.concatenate(
                [init_score[k * n_full + sel] for k in range(K)])

    md = Metadata()
    md.set_field("label", label)
    if weight is not None:
        md.set_field("weight", weight)
    if init_score is not None:
        md.set_field("init_score", init_score)
    if q is not None:
        md.set_field("group", q.astype(np.int32))
    elif query_inline is not None:
        # group column: consecutive identical ids form queries
        change = np.nonzero(np.diff(query_inline))[0] + 1
        boundaries = np.concatenate([[0], change, [len(query_inline)]])
        md.query_boundaries = boundaries.astype(np.int32)

    if reference is not None:
        ds = BinnedDataset.from_raw(X, config, reference=reference,
                                    metadata=md)
        return ds
    mappers = None
    if num_machines > 1 and allgather is None:
        # a host app may have injected its own collective backend
        # (LGBM_NetworkInitWithFunctions -> install_external_collectives)
        from .distributed import external_collectives
        ext = external_collectives()
        if ext is not None:
            allgather = ext.allgather
    if num_machines > 1 and allgather is not None:
        from .distributed import find_bins_distributed
        mappers = find_bins_distributed(X, config, rank, num_machines,
                                        allgather, cat_cols)
        if len(mappers) < X.shape[1]:
            # feature count synced DOWN to the min across ranks
            # (GlobalSyncUpByMin semantics): drop this rank's extras
            X = X[:, :len(mappers)]
            feature_names = feature_names[:len(mappers)]
            cat_cols = [c for c in cat_cols if c < len(mappers)]
    ds = BinnedDataset.from_raw(X, config, categorical_features=cat_cols,
                                feature_names=feature_names, metadata=md,
                                mappers=mappers,
                                bundle_allgather=(allgather if mappers
                                                  is not None else None),
                                rank=rank)
    if config.is_save_binary_file:
        ds.save_binary(bin_path[:-4])
        log_info(f"saved binary cache {bin_path}")
    return ds
