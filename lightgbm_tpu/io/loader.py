"""Text dataset loading: CSV / TSV / LibSVM with side files.

Counterpart of the reference ``DatasetLoader`` + ``Parser``
(`/root/reference/src/io/dataset_loader.cpp:159-219`, `src/io/parser.cpp`):
format auto-detection, ``label_column``/``ignore_column``/
``categorical_column`` handling (index ``N`` or ``name:xx`` syntax,
`config.h` IOConfig docs), side files ``.weight``/``.query``/``.init``
(`src/io/metadata.cpp` load paths), and distributed row sharding
(pre-partition or ``i % num_machines``, `dataset_loader.cpp:639-742`).

The inner parse runs through numpy (a C++ fast parser is the planned
native replacement; the format contract lives here).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..utils.file_io import localize
from ..utils.log import log_info, log_warning
from .dataset import BinnedDataset, Metadata


def detect_format(path: str, has_header: bool) -> str:
    """CSV vs TSV vs LibSVM auto-detection (reference Parser::CreateParser,
    src/io/parser.cpp format sniffing)."""
    with open(path) as f:
        lines = []
        for _ in range(32):
            ln = f.readline()
            if not ln:
                break
            lines.append(ln.rstrip("\n"))
    if has_header and lines:
        lines = lines[1:]
    if not lines:
        return "csv"
    sample = lines[0]
    if ":" in sample.split(",")[0].split("\t")[0].split(" ")[-1] \
            and any(":" in tok for tok in sample.split()[1:2]):
        return "libsvm"
    n_tab = sample.count("\t")
    n_comma = sample.count(",")
    if any(":" in tok for tok in sample.split()[1:]):
        return "libsvm"
    if n_tab >= n_comma and n_tab > 0:
        return "tsv"
    if n_comma > 0:
        return "csv"
    if " " in sample:
        return "libsvm" if ":" in sample else "tsv"
    return "csv"


def _parse_column_spec(spec: str, header_names: Optional[List[str]]) -> int:
    """Column spec: integer index or ``name:colname``."""
    if spec.startswith("name:"):
        name = spec[5:]
        if not header_names:
            raise ValueError(f"column {spec!r} needs a header")
        return header_names.index(name)
    return int(spec)


def _parse_multi_spec(spec: str, header_names) -> List[int]:
    if not spec:
        return []
    if spec.startswith("name:"):
        names = spec[5:].split(",")
        return [header_names.index(n) for n in names]
    return [int(s) for s in spec.replace(";", ",").split(",") if s != ""]


def parse_file(path: str, config: Config
               ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray],
                          Optional[np.ndarray], List[str], List[int]]:
    """-> (X, label, weight, query, feature_names, categorical_cols)."""
    path = localize(path)          # remote schemes -> temp copy (file_io)
    fmt = detect_format(path, config.has_header)
    header_names: Optional[List[str]] = None
    skip = 0
    if config.has_header:
        with open(path) as f:
            first = f.readline().rstrip("\n")
        sep = {"csv": ",", "tsv": "\t", "libsvm": " "}[fmt]
        header_names = first.split(sep)
        skip = 1

    weight_inline = None
    query_inline = None
    if fmt == "libsvm":
        from .. import native
        got = native.parse_libsvm(path, skip)
        if got is not None:
            X, label = got
        else:
            X, label = _parse_libsvm(path, skip)
        feature_names = [f"Column_{i}" for i in range(X.shape[1])]
        cat_cols: List[int] = []
    else:
        sep = "," if fmt == "csv" else "\t"
        from .. import native
        raw = native.parse_delimited(path, sep, skip)
        if raw is None:
            raw = np.genfromtxt(path, delimiter=sep, skip_header=skip,
                                dtype=np.float64)
        if raw.ndim == 1:
            raw = raw.reshape(-1, 1)
        ncol = raw.shape[1]
        label_idx = (_parse_column_spec(config.label_column, header_names)
                     if config.label_column else 0)
        drop = {label_idx}
        if config.weight_column:
            wi = _parse_column_spec(config.weight_column, header_names)
            weight_inline = raw[:, wi].astype(np.float32)
            drop.add(wi)
        if config.group_column:
            qi = _parse_column_spec(config.group_column, header_names)
            query_inline = raw[:, qi]
            drop.add(qi)
        for ig in _parse_multi_spec(config.ignore_column, header_names):
            drop.add(ig)
        keep = [i for i in range(ncol) if i not in drop]
        label = raw[:, label_idx].astype(np.float32)
        X = raw[:, keep]
        if header_names:
            feature_names = [header_names[i] for i in keep]
        else:
            feature_names = [f"Column_{i}" for i in range(len(keep))]
        cat_spec = config.categorical_column
        cat_cols = []
        if cat_spec:
            cat_orig = _parse_multi_spec(cat_spec, header_names)
            remap = {orig: j for j, orig in enumerate(keep)}
            cat_cols = [remap[c] for c in cat_orig if c in remap]
    from ..utils.file_io import release
    release(path)                       # free the localized copy now
    return X, label, weight_inline, query_inline, feature_names, cat_cols


def _parse_libsvm(path: str, skip: int) -> Tuple[np.ndarray, np.ndarray]:
    labels: List[float] = []
    rows: List[List[Tuple[int, float]]] = []
    max_idx = -1
    with open(path) as f:
        for i, line in enumerate(f):
            if i < skip:
                continue
            line = line.strip()
            if not line:
                continue
            toks = line.split()
            labels.append(float(toks[0]))
            feats = []
            for tok in toks[1:]:
                if ":" not in tok:
                    continue
                k, v = tok.split(":", 1)
                idx = int(k)
                feats.append((idx, float(v)))
                max_idx = max(max_idx, idx)
            rows.append(feats)
    X = np.zeros((len(rows), max_idx + 1), np.float64)
    for r, feats in enumerate(rows):
        for idx, v in feats:
            X[r, idx] = v
    return X, np.asarray(labels, np.float32)


def load_raw_matrix(path: str, has_header: bool = False
                    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Prediction-input parse: ``-> (X, label_or_None)`` with the same
    format autodetection and label-column convention as training files
    (reference Predictor file flow, `src/application/predictor.hpp:115+`
    reuses the training Parser, so column 0 / the LibSVM label token is
    stripped from the features)."""
    cfg = Config.from_params({"has_header": has_header})
    X, label, _, _, _, _ = parse_file(path, cfg)
    return X, label


def _load_side_file(path: str, dtype=np.float32) -> Optional[np.ndarray]:
    from ..utils.file_io import release
    try:
        local = localize(path)          # one remote round-trip, not two
    except FileNotFoundError:
        return None                     # absent side file — not an error
    if not os.path.exists(local):
        return None
    try:
        return np.loadtxt(local, dtype=dtype).reshape(-1)
    finally:
        release(local)


def load_file(path: str, config: Config,
              reference: Optional[BinnedDataset] = None,
              rank: int = 0, num_machines: int = 1,
              allgather=None) -> BinnedDataset:
    """Full file->BinnedDataset pipeline (reference
    DatasetLoader::LoadFromFile, dataset_loader.cpp:159-219), incl. the
    binary-cache fast path (SaveBinaryFile/CheckCanLoadFromBin).

    With ``num_machines > 1`` and an ``allgather`` collective, bin
    finding runs distributed: feature-sharded quantiles over the local
    row shard, mappers allgathered so every rank bins identically
    (`dataset_loader.cpp:816-880`; see ``io/distributed.py``)."""
    bin_path = path + ".bin.npz"
    is_local = "://" not in path
    # the cache stores whatever one process binned — single-machine,
    # local-FS only (a shard cache would hand other ranks the wrong rows,
    # and all ranks would race-write the same file)
    if (config.enable_load_from_binary_file and reference is None
            and num_machines == 1 and is_local
            and os.path.exists(bin_path)
            and os.path.getmtime(bin_path) >= os.path.getmtime(path)):
        log_info(f"loading binary cache {bin_path}")
        return BinnedDataset.load_binary(bin_path)

    X, label, weight, query_inline, feature_names, cat_cols = \
        parse_file(path, config)

    # side files (reference metadata.cpp LoadWeights/LoadQueryBoundaries/
    # LoadInitialScore)
    w = _load_side_file(path + ".weight")
    if w is not None:
        weight = w
    init_score = _load_side_file(path + ".init", np.float64)
    q = _load_side_file(path + ".query", np.int64)

    # distributed row sharding (dataset_loader.cpp:639-742): pre-partition
    # means each rank already has its own file; otherwise mod-rank rows
    if num_machines > 1 and not config.is_pre_partition:
        if q is not None or query_inline is not None:
            raise ValueError(
                "mod-rank row sharding would split ranking queries; use "
                "is_pre_partition=true with per-rank files (reference "
                "dataset_loader.cpp:639-742 contract)")
        n_full = len(X)
        sel = np.arange(rank, n_full, num_machines)
        X, label = X[sel], label[sel]
        if weight is not None:
            weight = weight[sel]
        if init_score is not None:
            # init_score is flat [n*num_class] in class-major blocks
            # (Metadata convention): take this rank's rows per block
            K = max(1, len(init_score) // n_full)
            init_score = np.concatenate(
                [init_score[k * n_full + sel] for k in range(K)])

    md = Metadata()
    md.set_field("label", label)
    if weight is not None:
        md.set_field("weight", weight)
    if init_score is not None:
        md.set_field("init_score", init_score)
    if q is not None:
        md.set_field("group", q.astype(np.int32))
    elif query_inline is not None:
        # group column: consecutive identical ids form queries
        change = np.nonzero(np.diff(query_inline))[0] + 1
        boundaries = np.concatenate([[0], change, [len(query_inline)]])
        md.query_boundaries = boundaries.astype(np.int32)

    if reference is not None:
        ds = BinnedDataset.from_raw(X, config, reference=reference,
                                    metadata=md)
        return ds
    mappers = None
    if num_machines > 1 and allgather is None:
        # a host app may have injected its own collective backend
        # (LGBM_NetworkInitWithFunctions -> install_external_collectives)
        from .distributed import external_collectives
        ext = external_collectives()
        if ext is not None:
            allgather = ext.allgather
    if num_machines > 1 and allgather is not None:
        from .distributed import find_bins_distributed
        mappers = find_bins_distributed(X, config, rank, num_machines,
                                        allgather, cat_cols)
        if len(mappers) < X.shape[1]:
            # feature count synced DOWN to the min across ranks
            # (GlobalSyncUpByMin semantics): drop this rank's extras
            X = X[:, :len(mappers)]
            feature_names = feature_names[:len(mappers)]
            cat_cols = [c for c in cat_cols if c < len(mappers)]
    ds = BinnedDataset.from_raw(X, config, categorical_features=cat_cols,
                                feature_names=feature_names, metadata=md,
                                mappers=mappers)
    if config.is_save_binary_file:
        ds.save_binary(bin_path[:-4])
        log_info(f"saved binary cache {bin_path}")
    return ds
