"""Developer tooling (not shipped with the library).

``tools.tpulint`` is importable (``python -m tools.tpulint``); the rest
of this directory is standalone scripts.
"""
