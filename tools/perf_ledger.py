#!/usr/bin/env python
"""Cross-round perf ledger over the committed ``BENCH_r*.json`` history.

Usage::

    python tools/perf_ledger.py [root] [--threshold 0.10] [--json]
    python tools/perf_ledger.py --check-readme [root]

Every driver round commits one ``BENCH_r<NN>.json`` artifact
(``{"n", "cmd", "rc", "tail", "parsed": {...}|null}``).  Until now the
history was read by hand: nothing flagged a regression against a past
round, and README figures cited artifacts informally (the ADVICE r5 #3
failure mode — two with-valid numbers, no one could say which run
backed which).  This tool mechanizes both:

* **Trend table** — one row per round, one column per tracked
  throughput metric (headline 1M / full 10.5M legs, bin255, the two
  ranking legs, serve, with-valid), plus ``peak_hbm_bytes`` and the
  ``attribution_*`` fractions once rounds start carrying the
  device-time attribution leg.  Unparsed rounds (driver timeouts —
  r05's rc=124) stay visible as ``parse:null`` rows instead of
  silently vanishing from the history.

* **Regression flag** — the NEWEST parsed round is compared per metric
  against the BEST prior parsed round; any metric more than
  ``--threshold`` (default 10%) below its best prior exits nonzero and
  names the metric, the value, and the round that set the bar.  Only
  the newest round is judged: historical dips are history, not news.

* **README figure provenance** (``--check-readme``) — every throughput
  or ratio figure inside the README's fenced measured-run blocks must
  either carry an explicit not-captured marker (``no citable``,
  ``pending``, ``artifact lost``, ``projected``) or name its source
  round (``BENCH_rNN``) — and the named artifact must actually contain
  a number within 15% of the claim.  This is the ratio-figure
  complement of tpulint's TPL008 (which can only check absolute
  ``M row-iters/s`` figures against the newest artifact): run as its
  own tier-1 gate (``tests/test_perf_ledger.py``).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

# tracked per-round metrics: (parsed key, short column label).  All
# higher-is-better throughputs/ratios — the regression rule below
# assumes that.
TRACKED: Tuple[Tuple[str, str], ...] = (
    ("value", "1M r-it/s"),
    ("full_row_iters_per_sec", "full r-it/s"),
    ("vs_baseline", "vs_base"),
    ("bin255_row_iters_per_sec", "bin255 r-it/s"),
    ("rank_doc_iters_per_sec", "rank d-it/s"),
    ("rank63_doc_iters_per_sec", "rank63 d-it/s"),
    ("serve_rows_per_sec", "serve rows/s"),
    ("valid_row_iters_per_sec", "valid r-it/s"),
    # fused multi-chip scan blocks (ISSUE 11): widest-mesh fused
    # row-iters/s and the fused-vs-per-iteration dispatch speedup,
    # derived from the leg's per-mesh-size multichip_table
    ("multichip_row_iters_per_sec", "mc r-it/s"),
    ("multichip_fused_speedup", "mc fused x"),
    # streamed out-of-core training at kernel speed (ISSUE 20): the
    # scale-phase streamed rows/s from the stream_ingest leg
    ("stream_rows_per_sec", "stream rows/s"),
)
ATTRIBUTION_KEYS = ("attribution_device_frac", "attribution_host_gap_frac",
                    "attribution_collective_frac")

_BENCH_RE = re.compile(r"BENCH_r(\d+)\.json")
_RATIO_RE = re.compile(r"(\d+(?:\.\d+)?)x\b")
_MFIG_RE = re.compile(r"(\d+(?:\.\d+)?)\s*M\s+(?:row|doc)-iters/s")
_ROUND_RE = re.compile(r"BENCH_r(\d+)")
UNCAPTURED_MARKERS = ("no citable", "pending", "artifact lost",
                      "projected", "uncaptured")
FIGURE_TOLERANCE = 0.15


def load_history(root: str) -> List[Dict[str, Any]]:
    """Every BENCH_r*.json under ``root``, oldest first.  A file that
    fails to read/parse still lands in the history (``error`` field):
    the ledger must render what IS committed, not a survivor subset."""
    out = []
    try:
        names = sorted(n for n in os.listdir(root) if _BENCH_RE.fullmatch(n))
    except OSError:
        return out
    for name in names:
        entry: Dict[str, Any] = {
            "round": int(_BENCH_RE.fullmatch(name).group(1)), "file": name}
        try:
            with open(os.path.join(root, name), encoding="utf-8") as f:
                data = json.load(f)
            entry["rc"] = data.get("rc")
            p = data.get("parsed")
            if isinstance(p, dict):
                # flatten the multichip table's widest-mesh row into
                # the tracked flat keys (rows are per mesh size)
                rows = p.get("multichip_table")
                if isinstance(rows, list) and rows:
                    widest = max(rows, key=lambda r: r.get("devices", 0))
                    p = dict(p)
                    p.setdefault("multichip_row_iters_per_sec",
                                 widest.get("row_iters_per_sec"))
                    p.setdefault("multichip_fused_speedup",
                                 widest.get("fused_speedup"))
            entry["parsed"] = p if isinstance(p, dict) else None
        except (OSError, ValueError) as exc:
            entry["error"] = f"{type(exc).__name__}: {exc}"
            entry["parsed"] = None
        out.append(entry)
    return out


def check_regressions(history: List[Dict[str, Any]],
                      threshold: float = 0.10) -> List[Dict[str, Any]]:
    """Newest parsed round vs the best prior parsed round, per metric.
    A metric missing from the newest round is NOT a regression (legs
    get budget-skipped legitimately; the bench's own gates police
    that) — only a metric that RAN and came in low flags."""
    parsed = [h for h in history if h["parsed"]]
    if len(parsed) < 2:
        return []
    newest, priors = parsed[-1], parsed[:-1]
    out = []
    for key, label in TRACKED:
        now = newest["parsed"].get(key)
        if not isinstance(now, (int, float)) or isinstance(now, bool):
            continue
        best, best_round = None, None
        for h in priors:
            v = h["parsed"].get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                if best is None or v > best:
                    best, best_round = float(v), h["round"]
        if best is None or best <= 0:
            continue
        if float(now) < (1.0 - threshold) * best:
            out.append({"metric": key, "label": label,
                        "round": newest["round"], "value": float(now),
                        "best_prior": best, "best_round": best_round,
                        "ratio": round(float(now) / best, 4)})
    return out


def _fmt(v) -> str:
    if v is None:
        return "·"
    if isinstance(v, float) and abs(v) >= 1e5:
        return f"{v / 1e6:.1f}M"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


def render_table(history: List[Dict[str, Any]], out=None) -> None:
    out = out if out is not None else sys.stdout   # late-bound: capsys
    p = lambda *a: print(*a, file=out)  # noqa: E731
    cols = [label for _, label in TRACKED]
    p(f"{'round':<7s} {'rc':>4s} " + " ".join(f"{c:>13s}" for c in cols)
      + f" {'peak_hbm':>10s}")
    p("-" * (13 + 14 * len(cols) + 11))
    best: Dict[str, float] = {}
    for h in history:
        parsed = h["parsed"]
        if parsed is None:
            reason = h.get("error", "parse:null (driver timeout class)")
            p(f"r{h['round']:<6d} {str(h.get('rc', '?')):>4s}  -- {reason}")
            continue
        cells = []
        for key, _ in TRACKED:
            v = parsed.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                v = float(v)
                prev = best.get(key)
                mark = ""
                if prev is not None and prev > 0:
                    if v < 0.9 * prev:
                        mark = "!"      # >10% below the best prior round
                    elif v > prev:
                        mark = "+"
                best[key] = max(prev or 0.0, v)
                cells.append(f"{_fmt(v)}{mark:<1s}".rjust(13))
            else:
                cells.append(f"{'·':>13s}")
        peak = parsed.get("peak_hbm_bytes")
        peak_s = f"{peak / 2**30:.2f}G" if isinstance(peak, int) else "·"
        p(f"r{h['round']:<6d} {str(h.get('rc', '?')):>4s} "
          + " ".join(cells) + f" {peak_s:>10s}")
        attrs = {k: parsed[k] for k in ATTRIBUTION_KEYS if k in parsed}
        if attrs:
            p("        attribution: " + "  ".join(
                f"{k.replace('attribution_', '')}={parsed[k]}"
                for k in ATTRIBUTION_KEYS if k in parsed))
    p("\n(+ = new best for that metric; ! = >10% below the best prior "
      "round; · = not captured that round)")


# ---------------------------------------------------------------------------
# README figure provenance
# ---------------------------------------------------------------------------
def _numeric_leaves(obj, out: List[float]) -> None:
    if isinstance(obj, dict):
        for v in obj.values():
            _numeric_leaves(v, out)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _numeric_leaves(v, out)
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out.append(float(obj))


def _fenced_entries(lines: List[str]) -> List[Tuple[int, str]]:
    """(first_lineno, text) per fenced-block ENTRY: a ``label:`` line
    plus its indented continuation lines — figures and their source
    labels may sit on different physical lines of one entry."""
    entries: List[Tuple[int, str]] = []
    in_fence = False
    cur: Optional[Tuple[int, List[str]]] = None
    for lineno, line in enumerate(lines, 1):
        if line.lstrip().startswith("```"):
            if in_fence and cur:
                entries.append((cur[0], "\n".join(cur[1])))
            in_fence, cur = not in_fence, None
            continue
        if not in_fence:
            continue
        if line[:1].isspace() and cur is not None:
            cur[1].append(line)
        else:
            if cur:
                entries.append((cur[0], "\n".join(cur[1])))
            cur = (lineno, [line])
    if cur:
        entries.append((cur[0], "\n".join(cur[1])))
    return entries


def check_readme(root: str) -> List[str]:
    """Findings for README fenced-block figures that neither carry an
    explicit not-captured marker nor name a source round containing
    a matching number.  Empty list = provenance clean."""
    path = os.path.join(root, "README.md")
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    artifacts = {h["round"]: h for h in load_history(root)}
    findings: List[str] = []
    for lineno, text in _fenced_entries(lines):
        low = text.lower()
        figures = ([("ratio", float(m)) for m in _RATIO_RE.findall(text)]
                   + [("mfig", float(m)) for m in _MFIG_RE.findall(text)])
        if not figures:
            continue
        if any(m in low for m in UNCAPTURED_MARKERS):
            continue
        rounds = [int(r) for r in _ROUND_RE.findall(text)]
        if not rounds:
            findings.append(
                f"README.md:{lineno}: measured figure(s) "
                f"{[f'{v}' for _, v in figures]} cite no source round — "
                f"add '(BENCH_rNN)' or an explicit not-captured marker")
            continue
        leaves: List[float] = []
        for r in rounds:
            h = artifacts.get(r)
            if h is None or h["parsed"] is None:
                findings.append(
                    f"README.md:{lineno}: cites BENCH_r{r:02d} but that "
                    f"artifact is missing or unparsed")
            else:
                _numeric_leaves(h["parsed"], leaves)
        if not leaves:
            continue
        for kind, claimed in figures:
            cands = [claimed] if kind == "ratio" else [claimed * 1e6]
            if kind == "mfig":
                cands.append(claimed)   # some keys record M directly
            ok = any(abs(c - v) <= FIGURE_TOLERANCE * max(abs(v), 1e-9)
                     for c in cands for v in leaves)
            if not ok:
                findings.append(
                    f"README.md:{lineno}: figure {claimed}"
                    f"{'x' if kind == 'ratio' else 'M'} not found within "
                    f"{int(FIGURE_TOLERANCE * 100)}% in cited round(s) "
                    f"{rounds} — re-measure or relabel with its real "
                    f"source run")
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="regression flag threshold vs best prior round")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--check-readme", action="store_true",
                    help="check README fenced figures name source rounds")
    args = ap.parse_args(argv)
    history = load_history(args.root)
    if args.check_readme:
        findings = check_readme(args.root)
        for f in findings:
            print(f)
        if not findings:
            print("README figure provenance: clean")
        return 1 if findings else 0
    if not history:
        print(f"no BENCH_r*.json under {args.root}", file=sys.stderr)
        return 2
    regressions = check_regressions(history, args.threshold)
    if args.json:
        print(json.dumps({"history": history, "regressions": regressions},
                         indent=1))
    else:
        render_table(history)
        for r in regressions:
            print(f"REGRESSION: {r['metric']} r{r['round']:02d} = "
                  f"{_fmt(r['value'])} is {100 * (1 - r['ratio']):.1f}% "
                  f"below best prior r{r['best_round']:02d} = "
                  f"{_fmt(r['best_prior'])}")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
