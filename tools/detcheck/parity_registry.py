"""The declarative parity/contract registry detcheck checks against.

PR 11's hard-won lesson was that two XLA programs computing the "same"
logic are only byte-identical when a TEST pins them together — chasing
cross-program FMA-contraction parity analytically is unwinnable.  This
file turns that lesson into a checked contract: every DUAL-PATH SEAM
(an env flag selecting between traced programs) and every ORDER-
SENSITIVE SELECTION (argmax/top_k in split-selection or serve code)
must either name the test that pins its parity / tie-break behavior,
or carry an explicit exemption with the argument why no gate is
needed.  A new seam that registers nothing is a DET004/DET005 finding.

Three tables:

* :data:`PROGRAM_PAIRS` — env-flag seams that select between traced
  programs, each mapped to the pinning test (DET005).  ``programs`` is
  documentation: the two (or more) compiled paths the flag chooses
  between.
* :data:`EXEMPT_ENV` — env knobs that look like seams to the analyzer
  (they gate branches in jit-bearing modules) but do NOT select
  between parity-relevant programs; each carries its why (DET005).
* :data:`TIE_BREAK` — modules whose ``argmax``/``argmin``/``top_k``
  calls decide model structure or served output, mapped to the test
  pinning the first-max tie-break (DET004).  A module can instead
  declare ``TIE_BREAK_CONTRACT = "<test path>"`` at module scope —
  the in-file form of the same registration.

Registered test paths are resolved against the REPO root (where this
tools/ package lives), not the analyzed root, so seeded-hazard tests
that copy ``lightgbm_tpu/`` into a temp dir still validate against the
real test suite.  A registered test whose file does not exist is itself
a finding (the gate rotted).
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

# repo root = parent of tools/ (this file lives in tools/detcheck/)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# -- DET005: env-flag program seams --------------------------------------
PROGRAM_PAIRS: Tuple[Dict, ...] = (
    {"name": "mesh-fused-vs-per-iteration",
     "env": "LGBM_TPU_MESH_BLOCK",
     "programs": ("fused lax.scan mesh block (one dispatch per window)",
                  "length-1 blocks of the same compiled body"),
     "test": "tests/test_mesh_block.py"},
    {"name": "block-vs-legacy-eager",
     "env": "LGBM_TPU_NO_BLOCK",
     "programs": ("fused scan-block training loop",
                  "legacy eager per-iteration loop"),
     "test": "tests/test_block_valid.py"},
    {"name": "fused-block-vs-per-iteration-serial",
     "env": "LGBM_TPU_NO_FUSED",
     "programs": ("fused 32-iteration serial block",
                  "per-iteration serial dispatches"),
     "test": "tests/test_block_valid.py"},
    {"name": "split-cache-vs-full-rescan",
     "env": "LGBM_TPU_SPLIT_CACHE",
     "programs": ("incremental per-leaf split cache (O(new children))",
                  "full O(L*F*B) per-wave rescan"),
     "test": "tests/test_split_cache.py"},
    {"name": "pallas-split-kernel-vs-xla-scan",
     "env": "LGBM_TPU_SPLIT_KERNEL",
     "programs": ("fused Pallas split kernel",
                  "chunked XLA scan split finder"),
     "test": "tests/test_pallas_split.py"},
    {"name": "split-kernel-interpret-vs-compiled",
     "env": "LGBM_TPU_SPLIT_INTERPRET",
     "programs": ("Pallas split kernel, interpret mode",
                  "Pallas split kernel, compiled"),
     "test": "tests/test_pallas_split.py"},
    {"name": "hist-backend-selection",
     "env": "LGBM_TPU_HIST_BACKEND",
     "programs": ("scatter histogram", "wide fused Pallas kernel",
                  "leaf-compacted Pallas kernel",
                  "their accumulator-seeded streamed-fold twins "
                  "(learner/serial.py make_hist_fold_fn; streamed=="
                  "resident per backend pinned by "
                  "tests/test_streaming.py)"),
     "test": "tests/test_compact.py"},
    {"name": "compact-vs-wide-kernel",
     "env": "LGBM_TPU_NO_COMPACT",
     "programs": ("leaf-compacted deep-wave histograms",
                  "wide fused route+hist kernel"),
     "test": "tests/test_compact.py"},
    {"name": "hist-mode-precision",
     "env": "LGBM_TPU_HIST_MODE",
     "programs": ("f32 histogram accumulation",
                  "bf16/int8h accumulation modes"),
     "test": "tests/test_hist_parity.py"},
    {"name": "donation-on-vs-off",
     "env": "LGBM_TPU_DONATE",
     "programs": ("score/grad/hess buffers donated in place",
                  "undonated dispatches"),
     "test": "tests/test_overlap.py"},
    {"name": "overlapped-vs-serial-psum",
     "env": "LGBM_TPU_OVERLAP",
     "programs": ("chunked double-buffered wave psum",
                  "single serial psum per wave"),
     "test": "tests/test_overlap.py"},
    {"name": "overlap-chunking",
     "env": "LGBM_TPU_OVERLAP_CHUNKS",
     "programs": ("N-chunk overlapped psum schedules (N >= 1)",),
     "test": "tests/test_overlap.py"},
    {"name": "phases-driver-vs-fused-build",
     "env": "LGBM_TPU_TIMETAG",
     "programs": ("unfused per-phase-timed wave driver",
                  "single jitted tree build"),
     "test": "tests/test_learner.py"},
    {"name": "lean-vs-padded-compile-shapes",
     "env": "LGBM_TPU_COMPILE_LEAN_ROWS",
     "programs": ("row-lean compile shapes", "padded compile shapes"),
     "test": "tests/test_consistency.py"},
    {"name": "device-vs-host-serve-scorer",
     "env": "LGBM_TPU_PREDICT_DEVICE",
     "programs": ("TPU-resident tensorized scorer (serve/compiler.py)",
                  "host numpy tree walk"),
     "test": "tests/test_serve.py"},
    {"name": "capi-device-vs-host-predict",
     "env": "LGBM_TPU_CAPI_DEVICE",
     "programs": ("C-API predict through the device scorer",
                  "C-API predict through the host walk"),
     "test": "tests/test_c_api.py"},
    {"name": "dart-keyed-vs-host-rng",
     "env": "LGBM_TPU_DART_HOST_RNG",
     "programs": ("pure (drop_seed, iteration)-keyed drop derivation",
                  "legacy stateful np.random.RandomState stream"),
     "test": "tests/test_determinism.py"},
    {"name": "stream-vs-resident",
     "env": "LGBM_TPU_STREAM_ROWS",
     "programs": ("streamed block trainer (boosting/streaming.py: "
                  "out-of-core mmap blocks, carried-accumulator "
                  "histogram folds — row-order scatter AND the "
                  "accumulator-seeded Pallas/compact kernel folds — "
                  "host-resident scores)",
                  "resident in-memory fused training loop"),
     "test": "tests/test_streaming.py"},
    {"name": "stream-pipeline-vs-serial",
     "env": "LGBM_TPU_STREAM_PIPELINE",
     "programs": ("depth-2 prefetch+staging upload/compute pipeline "
                  "(block k+1 staged and device_put before block k's "
                  "fold await; fold order unchanged)",
                  "serial stage->upload->fold->await escape hatch"),
     "test": "tests/test_streaming.py"},
    {"name": "elastic-vs-single-process",
     "env": "LGBM_TPU_ELASTIC",
     "programs": ("elastic multi-host streamed training (owned-shard "
                  "folds + allgathered partials combined in shard "
                  "order, barrier-snapshot recovery)",
                  "single-process streamed training at the same "
                  "protocol shard count"),
     "test": "tests/test_elastic.py"},
    {"name": "elastic-shard-protocol",
     "env": "LGBM_TPU_ELASTIC_SHARDS",
     "programs": ("S-shard partial folds for any fixed S (the run-"
                  "lifetime identity domain; world size and membership "
                  "history never reach the traced programs)",),
     "test": "tests/test_elastic.py"},
)

# knobs that branch inside jit-bearing modules but do not choose
# between parity-relevant traced programs — each with its argument
EXEMPT_ENV: Dict[str, str] = {
    "LGBM_TPU_PROFILE": "observability: windowed profiler capture; the "
                        "captured programs are the ones already running",
    "LGBM_TPU_PROFILE_WINDOWS": "profiler capture length knob",
    "LGBM_TPU_PROFILE_ITERS": "profiler capture length knob",
    "LGBM_TPU_COST_MODEL": "observability: extra cost_analysis() compile "
                           "feeds reporting only, never training state",
    "LGBM_TPU_TRACE": "observability: JSONL event trace destination",
    "LGBM_TPU_TRACE_CONTRACT": "observability: recompile accounting "
                               "around the same programs",
    "LGBM_TPU_MEM_CONTRACT": "observability: HBM watermark sampling",
    "LGBM_TPU_MEM_TOL_BYTES": "watermark tolerance knob",
    "LGBM_TPU_MEM_TOL_FRAC": "watermark tolerance knob",
    "LGBM_TPU_MEM_LEAK_ELEMS": "fault-injection sink sizing (tests)",
    "LGBM_TPU_DETERMINISM": "observability: the determinism contract "
                            "itself (digest sampling + RNG ledger)",
    "LGBM_TPU_NUM_CONTRACT": "observability: the runtime ulp contract "
                             "(obs/num_contract.py) — per-window "
                             "canonical-vs-f64-oracle drift ledger "
                             "riding the existing score fetch; "
                             "measures numerics, never changes them",
    "LGBM_TPU_FLIGHT_RECORDER": "observability: collective fingerprint "
                                "ring; never alters the schedule",
    "LGBM_TPU_FR_CAP": "flight-recorder ring size",
    "LGBM_TPU_FAULTS": "fault-injection arming (chaos runs)",
    "LGBM_TPU_OPS_PORT": "observability: live /metrics + /healthz + "
                         "/drain HTTP plane (obs/ops_plane.py); "
                         "host-side daemon thread mirroring the run "
                         "summary, never reaches traced programs",
    "LGBM_TPU_OPS_SKETCH": "ops-plane rolling quantile-sketch window "
                           "size; reporting resolution only",
    "LGBM_TPU_WATCHDOG_S": "observability: stall-watchdog deadline "
                           "(obs/health.py); the monitor thread only "
                           "observes a wedged dispatch, it never "
                           "alters what the device computes",
    "LGBM_TPU_SENTINELS": "observability: numerics sentinels riding "
                          "window-boundary host fetches; detection "
                          "only, model state untouched",
    "LGBM_TPU_SPIKE_FACTOR": "loss-spike sentinel threshold knob",
    "LGBM_TPU_FORENSIC": "stall-forensics output path override",
    "LGBM_TPU_SYNC_FREQ": "host stop-check cadence: changes when the "
                          "host LOOKS, not what the device computes",
    "LGBM_TPU_BLOCK_CAP": "watchdog bound on iterations per dispatch; "
                          "block length is byte-identical by "
                          "construction (tests/test_mesh_block.py)",
    "LGBM_TPU_COMPACT_SLOTS": "compact-backend wave threshold: backend "
                              "selection parity is pinned by "
                              "tests/test_compact.py",
    "LGBM_TPU_ROW_TILE": "kernel tiling knob; oracle parity in "
                         "tests/test_compact.py covers all tilings",
    "LGBM_TPU_SPLIT_VMEM_MB": "VMEM chunking budget; chunked==unchunked "
                              "bitwise in tests/test_split_cache.py",
    "LGBM_TPU_SPLIT_SCAN_MB": "VMEM chunking budget; chunked==unchunked "
                              "bitwise in tests/test_split_cache.py",
    "LGBM_TPU_SPLIT_CHUNK_F": "explicit chunk-width override; same "
                              "bitwise merge contract",
    "LGBM_TPU_RANK_CHUNK_PAIRS": "lambdarank pair-grid chunking; sums "
                                 "are order-preserving per bucket",
    "LGBM_TPU_PRED_TREE_CHUNK": "host predict chunking; per-tree sums "
                                "accumulate in tree order regardless",
    "LGBM_TPU_PRED_ROW_CHUNK": "host predict row chunking; rows are "
                               "independent",
    "LGBM_TPU_SERVE_ROW_CHUNK": "serve scorer row chunking; rows are "
                                "independent",
    "LGBM_TPU_NO_NATIVE": "parser backend (native C++ vs python); "
                          "parse parity pinned by tests/test_native_parser.py",
    "LGBM_TPU_COMPILE_CACHE": "persistent compile cache on/off; cached "
                              "executables are content-addressed",
    "LGBM_TPU_RETRY_ATTEMPTS": "retry policy knob",
    "LGBM_TPU_RETRY_BASE_S": "retry policy knob",
    "LGBM_TPU_RETRY_MAX_S": "retry policy knob",
    "LGBM_TPU_RETRY_DEADLINE_S": "retry policy knob",
    "LGBM_TPU_RETRY_JITTER": "retry backoff jitter; never reaches model "
                             "state",
    "LGBM_TPU_STREAM_CACHE": "out-of-core shard-cache directory "
                             "override (io/outofcore.py); storage "
                             "location only, the cache key still "
                             "validates content",
    "LGBM_TPU_COLLECTIVE_DEADLINE_S": "rank-loss detection deadline on "
                                      "host collectives (io/distributed."
                                      "deadline_call): bounds how long "
                                      "the HOST waits, never what the "
                                      "device computes",
    "LGBM_TPU_HEARTBEAT_S": "elastic heartbeat cadence (parallel/"
                            "elastic.py); liveness signaling only, "
                            "model state untouched",
    "LGBM_TPU_ELASTIC_MEMBER": "elastic member identity (stable "
                               "member id for rejoin/chaos kill "
                               "scheduling); naming only, the rank map "
                               "is the coordinator's",
    "LGBM_TPU_FLEET_LEDGER": "observability: coordinator ops-ledger "
                             "JSONL destination (obs/fleet.py); "
                             "append-only history of the fleet, never "
                             "read back into training",
    "LGBM_TPU_CLOCK_SYNC": "observability: per-rank coordinator-clock "
                           "offset estimation; stamps trace records "
                           "only, model state untouched",
    "LGBM_TPU_COLLECTIVE_SLOW": "fault-injection straggler delay "
                                "(collective.slow); a sleep before the "
                                "collective, identity-neutral",
    "LGBM_TPU_LOCK_CONTRACT": "observability: runtime lock-order "
                              "contract (obs/lock_contract.py) — "
                              "wrapped host locks record acquisition "
                              "order and wait/hold timing, never "
                              "touching what the device computes",
    "LGBM_TPU_LOCK_HOLD_S": "observability: held-past-deadline "
                            "threshold for contract-named locks; "
                            "reporting knob only",
    "LGBM_TPU_INTERLEAVE_SEEDS": "test harness: seed count for the "
                                 "tools/interleave.py schedule fuzzer; "
                                 "never read by library code",
}

# -- DET004: first-max tie-break contracts -------------------------------
TIE_BREAK: Dict[str, Dict] = {
    "lightgbm_tpu/ops/split.py": {
        "test": "tests/test_split_cache.py",
        "pins": "chunk merge reproduces the joint argmax first-max "
                "winner BITWISE (PR 9); full-rescan parity"},
    "lightgbm_tpu/ops/pallas_split.py": {
        "test": "tests/test_pallas_split.py",
        "pins": "packed-gain kernel argmax vs XLA-scan oracle, "
                "first-lowest-bin tie order"},
    "lightgbm_tpu/parallel/learners.py": {
        "test": "tests/test_parallel.py",
        "pins": "gathered-gain argmax and voting top_k produce "
                "serial-identical trees on 2-shard meshes"},
    "lightgbm_tpu/boosting/gbdt.py": {
        "test": "tests/test_engine.py",
        "pins": "feature-mask top_k over distinct uniforms; exactly-k "
                "contract and block/non-block mask identity"},
    "lightgbm_tpu/metric/metrics.py": {
        "exempt": "multiclass-error argmax feeds a scalar metric value, "
                  "never model structure or served output"},
    "lightgbm_tpu/sklearn.py": {
        "exempt": "predicted-class argmax: numpy documents first-max; a "
                  "tie needs exactly equal f64 probabilities"},
}


def seam_entry(env: str) -> Optional[Dict]:
    for entry in PROGRAM_PAIRS:
        if entry["env"] == env:
            return entry
    return None


def test_exists(test_path: str) -> bool:
    """Registered tests resolve against the repo root (tools/ anchor),
    so analyzing a copied package tree still sees the real suite."""
    return os.path.exists(os.path.join(REPO_ROOT, test_path))
