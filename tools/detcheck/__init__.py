"""detcheck — determinism & numerics analyzer.

The fourth static gate (after tpulint, spmdcheck, memcheck), aimed at
the property every bit-exactness test silently assumes: training and
serving are pure functions of (data, config, seeds).  Rules
DET001-DET006 (see ``rules.py``) run as a tier-1 gate via
``tests/test_detcheck.py`` / ``python -m tools.check`` and by hand::

    python -m tools.detcheck [--update-baseline] [--registry] [paths...]

Shares the analyzer plumbing in ``tools/analysis_core.py`` (one AST
parse per file per process, ``# detcheck: disable=DETxxx -- why``
suppressions, content-keyed baseline — committed EMPTY).  The
declarative contract lives in ``parity_registry.py`` (program-pair →
pinning test; tie-break contracts; exempted knobs).  The RUNTIME half
is the reproducibility contract (``lightgbm_tpu/obs/determinism.py``,
``LGBM_TPU_DETERMINISM=1``) and the train-twice replay harness
(``tools/replay_check.py``); this package only analyzes source.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from tools.analysis_core import (FileInfo, Finding, discover_files,
                                 load_baseline, new_findings, suppressed,
                                 write_baseline)

from .rules import FILE_RULES, PROJECT_RULES, RULE_TITLES, build_context

BASELINE_DEFAULT = os.path.join("tools", "detcheck", "baseline.json")

__all__ = [
    "run_detcheck", "Finding", "RULE_TITLES", "load_baseline",
    "write_baseline", "new_findings", "BASELINE_DEFAULT",
]


def run_detcheck(paths: Sequence[str] = ("lightgbm_tpu",),
                 root: Optional[str] = None,
                 project_rules: bool = True,
                 ) -> Tuple[List[Finding], Dict[str, FileInfo]]:
    """Analyze ``paths``; returns (findings sorted by location, FileInfo
    by relative path).  Inline suppressions applied; the baseline is NOT
    — callers diff via :func:`new_findings` (same contract as the other
    three analyzers).  ``project_rules=False`` skips the registry-
    soundness project rule for fixture runs."""
    root = os.path.abspath(root or os.getcwd())
    files = discover_files(paths, root)
    ctx = build_context(files, root, project_rules=project_rules)
    findings: List[Finding] = []
    for fi in files:
        for rule in FILE_RULES:
            for f in rule(fi, ctx):
                if not suppressed(fi, f):
                    findings.append(f)
    if project_rules:
        for rule in PROJECT_RULES:
            findings.extend(rule(ctx))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings, ctx.by_rel


def render_registry() -> List[str]:
    """Human-readable registry dump (the ``--registry`` CLI view)."""
    from . import parity_registry as reg
    lines = ["program seams (env -> pinning test):"]
    for e in reg.PROGRAM_PAIRS:
        mark = "" if reg.test_exists(e["test"]) else "  [MISSING TEST]"
        lines.append(f"  {e['env']:<28} {e['test']}{mark}")
    lines.append("exempt env knobs:")
    for env in sorted(reg.EXEMPT_ENV):
        lines.append(f"  {env:<28} {reg.EXEMPT_ENV[env]}")
    lines.append("tie-break contracts:")
    for rel in sorted(reg.TIE_BREAK):
        e = reg.TIE_BREAK[rel]
        what = e.get("test") or f"exempt: {e.get('exempt')}"
        lines.append(f"  {rel:<34} {what}")
    return lines
