"""detcheck rules DET001-DET006 — determinism & numerics hazards.

tpulint guards host-sync/recompile hazards, spmdcheck guards collective
schedules, memcheck guards device memory; detcheck guards the property
every bit-exactness test in the suite silently assumes: training and
serving are pure functions of (data, config, seeds).  The repo has paid
for this piecemeal three times — the PR 4/8 near-tie flip envelopes,
PR 11's cross-program FMA-contraction surrender, and ROADMAP item 5's
diagnosis that DART could not go multi-process because its drop RNG was
a stateful host ``np.random.RandomState``.

| id     | hazard                                                       |
|--------|--------------------------------------------------------------|
| DET001 | stateful / global host RNG: an ``np.random.RandomState`` /   |
|        | ``default_rng`` stored on an instance or module (hidden      |
|        | state across calls), a local one drawn from more than once   |
|        | or handed to another function (consumption ORDER becomes a   |
|        | hidden input — replay-hostile, rank-local), or a draw from   |
|        | the global ``np.random.*`` / ``random.*`` state.  Sanctioned |
|        | idioms: a keyed ``jax.random.fold_in`` derivation (pure in   |
|        | ``(seed, step)``), a fresh seeded generator consumed by ONE  |
|        | draw, or a counter-based ``np.random.Philox`` keyed by       |
|        | ``(seed, salt)``                                             |
| DET002 | ``jax.random`` key reuse: one key fed to two sampling sites  |
|        | (outside mutually exclusive branches) yields correlated —    |
|        | identical — draws; fold_in/split a fresh subkey per site     |
| DET003 | iteration over a ``set`` (literal, ``set()``, comprehension):|
|        | order is unspecified and PYTHONHASHSEED-dependent for str    |
|        | keys — poison for traced operand order, model text, or       |
|        | collective schedules.  ``sorted(...)`` the set first         |
| DET004 | ``argmax``/``argmin``/``top_k`` without a registered         |
|        | first-max tie-break contract: tie order IS model structure   |
|        | (the PR 9 bitwise chunk-merge invariant).  Register the      |
|        | pinning test in tools/detcheck/parity_registry.py TIE_BREAK, |
|        | or declare module-level ``TIE_BREAK_CONTRACT = "<test>"``    |
| DET005 | an env flag gating a branch in a jit-bearing module — a      |
|        | dual-path program seam — that names no parity gate: register |
|        | the pinning test in parity_registry.PROGRAM_PAIRS or exempt  |
|        | it with an argument in EXEMPT_ENV                            |
| DET006 | time / env / datetime reads inside traced scope: the value   |
|        | constant-folds at trace time, so two processes (or two runs) |
|        | tracing under different clocks/environments compile          |
|        | DIFFERENT programs that claim to be the same                 |

Suppression: ``# detcheck: disable=DETxxx -- why`` (shared
analysis_core syntax; an undocumented disable is tpulint TPL000).
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis_core import FileInfo, Finding
from tools.tpulint.callgraph import (FunctionInfo, _callee_name,
                                     compute_traced)
from tools.tpulint.rules import NP_ALIASES, _root_name, _walk_own

from . import parity_registry

RULE_TITLES = {
    "DET001": "stateful / global host RNG on a training or serving path",
    "DET002": "jax.random key reused across sampling sites",
    "DET003": "iteration over an unordered set",
    "DET004": "argmax/top_k without a registered tie-break contract",
    "DET005": "dual-path program seam without a registered parity gate",
    "DET006": "time/env read inside traced scope",
}

# np.random.* draws that consume the GLOBAL numpy RNG state
_GLOBAL_NP_DRAWS = {
    "rand", "randn", "random", "random_sample", "uniform", "normal",
    "choice", "permutation", "shuffle", "randint", "binomial", "beta",
    "gamma", "poisson", "exponential", "sample", "standard_normal",
    "seed", "bytes",
}
# stdlib random-module draws
_STDLIB_RANDOM_DRAWS = {
    "random", "uniform", "randint", "randrange", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "betavariate",
    "expovariate", "seed", "getrandbits", "triangular",
}
# jax.random samplers whose FIRST argument is a consumed key
_JAX_SAMPLERS = {
    "uniform", "normal", "bernoulli", "randint", "choice", "permutation",
    "gumbel", "truncated_normal", "categorical", "exponential", "laplace",
    "beta", "gamma", "poisson", "bits", "rademacher", "dirichlet",
    "shuffle",
}
_KEY_DERIVERS = {"PRNGKey", "key", "fold_in", "split"}

_TIME_READS = {"time", "perf_counter", "monotonic", "time_ns",
               "process_time", "perf_counter_ns", "monotonic_ns"}
_DATETIME_READS = {"now", "utcnow", "today"}

# traced-program constructs whose presence makes a module "jit-bearing"
# for DET005 (an env branch in such a module can select what compiles)
_PROGRAM_MARKERS = {"jit", "pjit", "pallas_call", "shard_map", "scan",
                    "fori_loop", "while_loop"}


@dataclass
class DetContext:
    root: str
    files: List[FileInfo]
    by_rel: Dict[str, FileInfo]
    functions: Dict[str, FunctionInfo]
    traced: Set[str]
    project_rules: bool = True


def build_context(files: Sequence[FileInfo], root: str,
                  project_rules: bool = True) -> DetContext:
    functions, traced = compute_traced(files)
    return DetContext(root=root, files=list(files),
                      by_rel={fi.rel: fi for fi in files},
                      functions=functions, traced=traced,
                      project_rules=project_rules)


# -- shared helpers -------------------------------------------------------
def _dotted(node: ast.AST) -> Optional[str]:
    """Full dotted name of an attribute chain: ``np.random.rand`` ->
    "np.random.rand"; None when any link is not a Name/Attribute."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _imports_module(fi: FileInfo, name: str) -> Set[str]:
    """Aliases under which module ``name`` is imported ('random' ->
    {'random'} for ``import random``, {'rnd'} for ``as rnd``)."""
    out: Set[str] = set()
    for node in ast.walk(fi.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == name:
                    out.add(a.asname or a.name)
    return out


def _enclosing_functions(fi: FileInfo):
    """Yield every def (incl. nested) in the file."""
    for node in ast.walk(fi.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# -- DET001 ---------------------------------------------------------------
def _is_rng_ctor(call: ast.Call) -> bool:
    name = _callee_name(call.func)
    return name in ("RandomState", "default_rng")


def rule_det001(fi: FileInfo, ctx: DetContext) -> List[Finding]:
    out: List[Finding] = []

    def flag(node: ast.AST, what: str, fix: str) -> None:
        out.append(Finding(
            fi.rel, node.lineno, "DET001",
            f"{what}: stateful host RNG on a training/serving path is "
            f"replay-hostile (resume/rank divergence — the DART drop-RNG "
            f"class, ROADMAP item 5); {fix}"))

    random_aliases = _imports_module(fi, "random")

    # (a) global-state draws: np.random.<draw>() / random.<draw>()
    for node in ast.walk(fi.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted is None:
            continue
        parts = dotted.split(".")
        if (len(parts) == 3 and parts[0] in NP_ALIASES
                and parts[1] == "random" and parts[2] in _GLOBAL_NP_DRAWS):
            flag(node, f"draw from the global numpy RNG ({dotted})",
                 "derive from an explicit seed: jax.random.fold_in for "
                 "device paths, or a fresh single-draw "
                 "np.random.Philox/RandomState(seed) on the host")
        elif (len(parts) == 2 and parts[0] in random_aliases
                and parts[1] in _STDLIB_RANDOM_DRAWS):
            flag(node, f"draw from the global stdlib RNG ({dotted})",
                 "thread an explicit seeded generator, or justify-"
                 "suppress when the draw can never reach model state")

    # (b) RandomState/default_rng constructions: stateful if stored on
    # self/module, sequential if a local is drawn from more than once
    # or escapes into another call
    for node in ast.walk(fi.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _is_rng_ctor(node.value)):
            continue
        ctor = _callee_name(node.value.func)
        stored = None
        local = None
        for t in node.targets:
            if isinstance(t, ast.Attribute):     # self._rng = ...
                stored = t
            elif isinstance(t, ast.Name):
                local = t.id
        if stored is not None:
            flag(node, f"{ctor} stored on an instance/module attribute",
                 "replace with a pure (seed, step)-keyed "
                 "jax.random.fold_in derivation (the bagging/feature-"
                 "mask idiom, boosting/gbdt.py)")
            continue
        if local is None:
            continue
        # module-scope assignment = process-lifetime state
        if node in fi.tree.body:
            flag(node, f"{ctor} bound at module scope",
                 "construct per call from an explicit seed")
            continue
        uses = _rng_uses(fi, node, local)
        if len(uses) > 1:
            flag(node, f"{ctor} local `{local}` consumed by "
                 f"{len(uses)} draw sites",
                 "sequential draw order is a hidden input: derive each "
                 "draw from its own (seed, salt) key — hash-based "
                 "permutation / np.random.Philox(key=[seed, salt]) — or "
                 "collapse to one draw")
    return out


def _rng_uses(fi: FileInfo, assign: ast.Assign, name: str) -> List[int]:
    """Draw/escape sites of RNG local ``name`` belonging to THIS
    assignment: method calls ``name.x(...)`` and ``name`` passed as a
    call argument (an escape we can't count = at least one opaque draw
    site), bounded by the next reassignment of the same name (two
    sibling ``rng = RandomState(...)`` branches each own their draws)."""
    fn = _innermost_function(fi, assign)
    scope = fn if fn is not None else fi.tree
    next_assign = min((n.lineno for n in ast.walk(scope)
                       if isinstance(n, ast.Assign) and n is not assign
                       and n.lineno > assign.lineno
                       and any(isinstance(t, ast.Name) and t.id == name
                               for t in n.targets)),
                      default=1 << 30)
    uses: List[int] = []
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            in_range = assign.lineno <= node.lineno < next_assign
            f = node.func
            if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                    and f.value.id == name and in_range):
                uses.append(node.lineno)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if (isinstance(arg, ast.Name) and arg.id == name
                        and in_range):
                    uses.append(node.lineno)
    return uses


_FN_CACHE: Dict[str, List[Tuple[ast.AST, Set[int]]]] = {}


def _innermost_function(fi: FileInfo, node: ast.AST) -> Optional[ast.AST]:
    """Innermost def containing ``node`` (by identity)."""
    best = None
    for fn in _enclosing_functions(fi):
        for sub in ast.walk(fn):
            if sub is node:
                best = fn            # later (nested) defs win: ast.walk
                break                # yields outer defs before inner ones
    return best


# -- DET002 ---------------------------------------------------------------
def _branch_path(fn: ast.AST, target: ast.AST) -> List[Tuple[int, int]]:
    """[(id(if_node), arm)] chain of If/IfExp ancestors of ``target``
    inside ``fn`` (arm 0 = body, 1 = orelse)."""
    path: List[Tuple[int, int]] = []

    def walk(node: ast.AST, acc: List[Tuple[int, int]]) -> bool:
        if node is target:
            path.extend(acc)
            return True
        if isinstance(node, (ast.If, ast.IfExp)):
            body = node.body if isinstance(node.body, list) else [node.body]
            orelse = (node.orelse if isinstance(node.orelse, list)
                      else [node.orelse])
            for child in ast.iter_child_nodes(node):
                in_body = any(child is b or _contains(b, child)
                              for b in body)
                in_else = any(child is o or _contains(o, child)
                              for o in orelse)
                arm = 0 if in_body else (1 if in_else else -1)
                nxt = acc + [(id(node), arm)] if arm >= 0 else acc
                if walk(child, nxt):
                    return True
            return False
        for child in ast.iter_child_nodes(node):
            if walk(child, acc):
                return True
        return False

    walk(fn, [])
    return path


def _contains(parent: ast.AST, node: ast.AST) -> bool:
    return any(sub is node for sub in ast.walk(parent))


def _exclusive(p1: List[Tuple[int, int]], p2: List[Tuple[int, int]]) -> bool:
    d1, d2 = dict(p1), dict(p2)
    return any(d1[k] != d2[k] for k in d1.keys() & d2.keys())


def rule_det002(fi: FileInfo, ctx: DetContext) -> List[Finding]:
    if "jax" not in fi.source:
        return []
    out: List[Finding] = []
    for fn in _enclosing_functions(fi):
        # key-name assignment lines (PRNGKey/fold_in/split results)
        assigns: Dict[str, List[int]] = {}
        for node in _walk_own(fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, (ast.Call, ast.Subscript)):
                call = node.value
                if isinstance(call, ast.Subscript):
                    call = call.value
                if (isinstance(call, ast.Call)
                        and _callee_name(call.func) in _KEY_DERIVERS):
                    for t in node.targets:
                        names = (t.elts if isinstance(t, (ast.Tuple,
                                                          ast.List))
                                 else [t])
                        for tt in names:
                            if isinstance(tt, ast.Name):
                                assigns.setdefault(tt.id, []).append(
                                    node.lineno)
        if not assigns:
            continue
        # sampler consumption sites per key name
        uses: Dict[str, List[ast.Call]] = {}
        for node in _walk_own(fn):
            if (isinstance(node, ast.Call)
                    and _callee_name(node.func) in _JAX_SAMPLERS
                    and node.args and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in assigns):
                uses.setdefault(node.args[0].id, []).append(node)
        for name, sites in uses.items():
            if len(sites) < 2:
                continue
            sites.sort(key=lambda n: n.lineno)
            paths = [_branch_path(fn, s) for s in sites]
            for j in range(1, len(sites)):
                prior = None
                for i in range(j):
                    refolded = any(
                        sites[i].lineno < a <= sites[j].lineno
                        for a in assigns[name])
                    if not refolded and not _exclusive(paths[i], paths[j]):
                        prior = sites[i]
                        break
                if prior is not None:
                    out.append(Finding(
                        fi.rel, sites[j].lineno, "DET002",
                        f"key `{name}` already consumed by a sampler at "
                        f"line {prior.lineno}: reusing a jax.random key "
                        f"yields IDENTICAL draws, silently correlating "
                        f"the two sites; fold_in a distinct salt per "
                        f"site (key = jax.random.fold_in(key, site_id))"))
    return out


# -- DET003 ---------------------------------------------------------------
def _is_set_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return (isinstance(node, ast.Call)
            and _callee_name(node.func) in ("set", "frozenset"))


def _set_assignments(scope: ast.AST
                     ) -> Tuple[Dict[str, List[int]], Dict[str, List[int]]]:
    """name -> sorted linenos of ``name = <set expr>`` assignments in
    ``scope`` (one pass, so Name resolution below is a dict lookup)."""
    out: Dict[str, List[int]] = {}
    nonset: Dict[str, List[int]] = {}
    for sub in _walk_own(scope):
        if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)):
            dest = out if _is_set_literal(sub.value) else nonset
            dest.setdefault(sub.targets[0].id, []).append(sub.lineno)
    # a later non-set reassignment shadows: keep both tables
    return {n: sorted(ls) for n, ls in out.items()}, \
        {n: sorted(ls) for n, ls in nonset.items()}


def _is_set_expr(node: ast.AST, tables) -> bool:
    if _is_set_literal(node):
        return True
    if isinstance(node, ast.Name) and tables is not None:
        sets, nonsets = tables
        prior_set = max((l for l in sets.get(node.id, ())
                         if l <= node.lineno), default=None)
        if prior_set is None:
            return False
        prior_non = max((l for l in nonsets.get(node.id, ())
                         if l <= node.lineno), default=-1)
        return prior_set > prior_non
    return False


def rule_det003(fi: FileInfo, ctx: DetContext) -> List[Finding]:
    if "set" not in fi.source:
        return []
    out: List[Finding] = []

    def flag(node: ast.AST, how: str) -> None:
        out.append(Finding(
            fi.rel, node.lineno, "DET003",
            f"{how} a set: iteration order is unspecified (and "
            f"PYTHONHASHSEED-dependent for strings) — if it reaches "
            f"traced operand order, model text, or a collective "
            f"schedule, two runs diverge; iterate `sorted(...)` of it"))

    for fn in list(_enclosing_functions(fi)) + [None]:
        scope = fn if fn is not None else fi.tree
        tables = _set_assignments(scope)
        for node in _walk_own(scope):
            iters: List[ast.AST] = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                   ast.DictComp, ast.SetComp)):
                iters.extend(g.iter for g in node.generators)
            elif (isinstance(node, ast.Call)
                  and _callee_name(node.func) in ("list", "tuple",
                                                  "enumerate", "reversed")
                  and node.args):
                iters.append(node.args[0])
            for it in iters:
                if _is_set_expr(it, tables):
                    flag(node, "iterating")
    return out


# -- DET004 ---------------------------------------------------------------
_ORDER_SENSITIVE = {"argmax", "argmin", "top_k"}


def _declares_tie_break(fi: FileInfo) -> bool:
    for node in fi.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "TIE_BREAK_CONTRACT"
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            return True
    return False


def rule_det004(fi: FileInfo, ctx: DetContext) -> List[Finding]:
    calls = [n for n in ast.walk(fi.tree)
             if isinstance(n, ast.Call)
             and _callee_name(n.func) in _ORDER_SENSITIVE]
    if not calls:
        return []
    entry = parity_registry.TIE_BREAK.get(fi.rel)
    if entry is not None:
        if "exempt" in entry:
            return []
        test = entry.get("test", "")
        if parity_registry.test_exists(test):
            return []
        return [Finding(
            fi.rel, calls[0].lineno, "DET004",
            f"tie-break contract registered but its pinning test "
            f"`{test}` does not exist: the gate rotted — restore the "
            f"test or re-register")]
    if _declares_tie_break(fi):
        return []
    return [Finding(
        fi.rel, c.lineno, "DET004",
        f"`{_callee_name(c.func)}` selects among candidates with no "
        f"registered first-max tie-break contract: tie order IS model "
        f"structure / served output (the PR 9 bitwise chunk-merge "
        f"invariant); register the pinning test in tools/detcheck/"
        f"parity_registry.py TIE_BREAK or declare TIE_BREAK_CONTRACT "
        f"at module scope") for c in calls]


# -- DET005 ---------------------------------------------------------------
def _env_read_name(node: ast.Call) -> Optional[str]:
    """Constant env-var name of environ.get(...)/getenv(...) calls."""
    f = node.func
    name = None
    if isinstance(f, ast.Attribute) and f.attr in ("get", "getenv"):
        base = _dotted(f.value) or ""
        if f.attr == "get" and not base.endswith("environ"):
            return None
        name = node.args[0] if node.args else None
    elif isinstance(f, ast.Name) and f.id == "getenv":
        name = node.args[0] if node.args else None
    if (isinstance(name, ast.Constant) and isinstance(name.value, str)):
        return name.value
    return None


def _module_has_program_markers(fi: FileInfo) -> bool:
    for node in ast.walk(fi.tree):
        if isinstance(node, ast.Call) \
                and _callee_name(node.func) in _PROGRAM_MARKERS:
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _callee_name(dec) in _PROGRAM_MARKERS:
                    return True
    return False


def rule_det005(fi: FileInfo, ctx: DetContext) -> List[Finding]:
    if "environ" not in fi.source and "getenv" not in fi.source:
        return []
    if not _module_has_program_markers(fi):
        return []
    # env reads that CONTROL a branch: inside an If/IfExp/While test,
    # or inside a Compare / membership expression anywhere (the
    # `environ.get("X", "1") != "0"` seam-predicate idiom — callers
    # branch on the returned bool)
    test_spans: List[ast.AST] = []
    for node in ast.walk(fi.tree):
        if isinstance(node, (ast.If, ast.IfExp, ast.While)):
            test_spans.append(node.test)
        elif isinstance(node, ast.Compare):
            test_spans.append(node)
    out: List[Finding] = []
    seen: Set[Tuple[int, str]] = set()
    for span in test_spans:
        for node in ast.walk(span):
            if not isinstance(node, ast.Call):
                continue
            env = _env_read_name(node)
            if env is None or not env.startswith("LGBM_"):
                continue
            if (node.lineno, env) in seen:
                continue
            seen.add((node.lineno, env))
            entry = parity_registry.seam_entry(env)
            if entry is not None:
                if parity_registry.test_exists(entry["test"]):
                    continue
                out.append(Finding(
                    fi.rel, node.lineno, "DET005",
                    f"program seam `{env}` is registered but its parity "
                    f"gate `{entry['test']}` does not exist: restore the "
                    f"test or re-register"))
            elif env not in parity_registry.EXEMPT_ENV:
                out.append(Finding(
                    fi.rel, node.lineno, "DET005",
                    f"env flag `{env}` gates a branch in a jit-bearing "
                    f"module — a dual-path program seam with NO "
                    f"registered parity gate (the PR 11 lesson: two "
                    f"programs are only byte-identical when a test pins "
                    f"them); add a PROGRAM_PAIRS entry mapping it to "
                    f"its pinning test in tools/detcheck/"
                    f"parity_registry.py, or EXEMPT_ENV it with an "
                    f"argument"))
    return out


# -- DET006 ---------------------------------------------------------------
def _env_contract_covered(env: Optional[str]) -> bool:
    """Env names already under the DET005 parity contract (a registered
    seam or an exempted knob) are DECLARED trace-time inputs — their
    cross-program story is pinned elsewhere, so DET006 stays quiet."""
    if env is None:
        return False
    return (parity_registry.seam_entry(env) is not None
            or env in parity_registry.EXEMPT_ENV)


def rule_det006(fi: FileInfo, ctx: DetContext) -> List[Finding]:
    out: List[Finding] = []
    traced_here = [info for q, info in ctx.functions.items()
                   if q in ctx.traced and info.fi.rel == fi.rel]

    def flag(node: ast.AST, what: str) -> None:
        out.append(Finding(
            fi.rel, node.lineno, "DET006",
            f"{what} inside traced scope: the value constant-folds at "
            f"TRACE time, so two processes (or a retrace) compile "
            f"different programs that claim to be the same computation; "
            f"read it on the host and pass the value in as an operand "
            f"or static arg (or register the knob as a seam in "
            f"tools/detcheck/parity_registry.py)"))

    time_aliases = _imports_module(fi, "time") | {"time"}
    for info in traced_here:
        for node in _walk_own(info.node):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func) or ""
                parts = dotted.split(".")
                if (len(parts) == 2 and parts[0] in time_aliases
                        and parts[1] in _TIME_READS):
                    flag(node, f"{dotted}() clock read")
                elif (len(parts) >= 2 and parts[-1] in _DATETIME_READS
                      and "datetime" in parts[:-1]):
                    flag(node, f"{dotted}() clock read")
                elif ((_dotted(node.func) or "").endswith((
                        "environ.get", "os.getenv"))
                        or isinstance(node.func, ast.Name)
                        and node.func.id == "getenv"):
                    env = _env_read_name(node)
                    if not _env_contract_covered(env):
                        flag(node, f"environment read (`{env or '?'}`)")
            elif (isinstance(node, ast.Subscript)
                  and (_dotted(node.value) or "").endswith("environ")
                  and not (isinstance(node.slice, ast.Constant)
                           and _env_contract_covered(node.slice.value))):
                flag(node, "os.environ[...] read")
    return out


FILE_RULES: List[Callable[[FileInfo, DetContext], List[Finding]]] = [
    rule_det001, rule_det002, rule_det003, rule_det004, rule_det005,
    rule_det006,
]


# -- project rule: the registry itself must be sound ----------------------
def rule_registry_sound(ctx: DetContext) -> List[Finding]:
    """Every registered parity gate / tie-break test must exist, and no
    env is both a PROGRAM_PAIRS seam and EXEMPT (ambiguous contract)."""
    reg_rel = "tools/detcheck/parity_registry.py"
    out: List[Finding] = []
    seam_envs = set()
    for entry in parity_registry.PROGRAM_PAIRS:
        seam_envs.add(entry["env"])
        if not parity_registry.test_exists(entry["test"]):
            out.append(Finding(
                reg_rel, 1, "DET005",
                f"PROGRAM_PAIRS entry `{entry['name']}` names missing "
                f"test `{entry['test']}`"))
    for env in seam_envs & set(parity_registry.EXEMPT_ENV):
        out.append(Finding(
            reg_rel, 1, "DET005",
            f"`{env}` is both a registered seam and exempt: pick one"))
    for rel, entry in parity_registry.TIE_BREAK.items():
        if "exempt" not in entry and not parity_registry.test_exists(
                entry.get("test", "")):
            out.append(Finding(
                reg_rel, 1, "DET004",
                f"TIE_BREAK entry for `{rel}` names missing test "
                f"`{entry.get('test')}`"))
    return out


PROJECT_RULES = [rule_registry_sound]
