"""The declarative lock registry — concheck's ground truth.

Every lock in the package is declared here: which module/class owns
it, which mutable names it guards, and (as a DAG) the only order in
which locks may nest.  The static rules (``rules.py``) check the code
against these declarations; the runtime contract
(``obs/lock_contract.py``) gives the SAME lock names to its wrapped
locks, so a static CON002 finding and a runtime cycle report name the
same edge.

Declaration schema (one dict per lock)::

    {"name": "telemetry",                    # registry-wide unique id
     "module": "lightgbm_tpu/obs/telemetry.py",
     "cls": None,                            # owning class, None = module
     "attr": "_lock",                        # the variable holding it
     "kind": "rlock",                        # lock | rlock | condition
     "guards": ("_counters", ...),           # names only THIS lock guards
     "assume_held": ("_trace_write",)}       # helpers whose docstring
                                             # contract is "caller holds
                                             # the lock" — their writes
                                             # are treated as guarded

``ORDER`` declares the permitted nesting DAG as ``(outer, inner)``
edges; nesting is allowed along any DAG *path* (declared edges are
transitive), re-entry of the same rlock/condition is always allowed,
and everything else is a CON002.  Keep the DAG minimal: an edge is a
claim that holding ``outer`` while acquiring ``inner`` is deliberate.

``CALLBACKS`` names the user-supplied-callback seams (CON005): a call
through one of these names under a held lock is flagged unless the
entry carries a ``safe`` justification (which must argue the callback's
reachable set only ever takes declared-leaf locks).

Fixture/out-of-tree modules can declare the same facts in-file::

    CONCHECK_LOCKS = {"_lock": ("shared_counter",)}
    CONCHECK_ORDER = (("_lock_a", "_lock_b"),)
    CONCHECK_ASSUME_HELD = ("_helper",)
    CONCHECK_CALLBACKS = ("_callback",)

In-file lock names render as ``<basename>:<attr>``.
"""
from __future__ import annotations

from typing import Dict, Tuple

# ---------------------------------------------------------------------------
# locks
# ---------------------------------------------------------------------------
LOCKS: Tuple[Dict, ...] = (
    # -- telemetry: the per-process metrics spine ----------------------
    {"name": "telemetry", "module": "lightgbm_tpu/obs/telemetry.py",
     "cls": None, "attr": "_lock", "kind": "rlock",
     "guards": ("_enabled", "_trace_requested", "_trace_file",
                "_trace_open_path", "_spans", "_counters", "_gauges",
                "_events", "_sections", "_held"),
     # "Caller holds _lock" is these helpers' documented contract
     "assume_held": ("_trace_write",)},
    # MetricsRegistry is the telemetry SINK: leaf-level by design —
    # taken inside the telemetry lock on the write path (see ORDER)
    {"name": "metrics_registry", "module": "lightgbm_tpu/obs/ops_plane.py",
     "cls": "MetricsRegistry", "attr": "_lock", "kind": "lock",
     "guards": ("counters", "gauges", "events", "spans")},
    {"name": "ops_plane", "module": "lightgbm_tpu/obs/ops_plane.py",
     "cls": None, "attr": "_lock", "kind": "lock",
     "guards": ("_plane",)},
    {"name": "ops_drain", "module": "lightgbm_tpu/obs/ops_plane.py",
     "cls": "OpsPlane", "attr": "_hooks_lock", "kind": "lock",
     "guards": ("_drain_hooks",)},
    # -- health state machine + stall watchdog -------------------------
    {"name": "health", "module": "lightgbm_tpu/obs/health.py",
     "cls": None, "attr": "_lock", "kind": "rlock",
     "guards": ("_active", "_state")},
    {"name": "watchdog", "module": "lightgbm_tpu/obs/health.py",
     "cls": "Watchdog", "attr": "_cv", "kind": "condition",
     "guards": ("_armed", "_seq", "_stop")},
    # -- collective flight recorder ------------------------------------
    {"name": "flight_recorder",
     "module": "lightgbm_tpu/obs/flight_recorder.py",
     "cls": None, "attr": "_lock", "kind": "lock",
     "guards": ("_ring", "_count", "_digest")},
    # -- fleet accounting + the coordinator ledger ---------------------
    {"name": "fleet", "module": "lightgbm_tpu/obs/fleet.py",
     "cls": None, "attr": "_lock", "kind": "lock",
     "guards": ("_clock", "_seqs", "_skew", "_episodes")},
    {"name": "fleet_ledger", "module": "lightgbm_tpu/obs/fleet.py",
     "cls": "FleetLedger", "attr": "_wlock", "kind": "lock",
     "guards": ("_fd",)},
    # -- compile tracker (jax log handler runs on jax's threads) -------
    {"name": "trace_contract",
     "module": "lightgbm_tpu/obs/trace_contract.py",
     "cls": "CompileTracker", "attr": "_lock", "kind": "lock",
     "guards": ("_events", "_steady_idx")},
    # -- runtime lock contract's own graph lock (leaf everywhere) ------
    {"name": "lock_contract", "module": "lightgbm_tpu/obs/lock_contract.py",
     "cls": None, "attr": "_graph_lock", "kind": "lock",
     "guards": ("_edges", "_violations", "_stats")},
    # -- serving worker ------------------------------------------------
    {"name": "serve", "module": "lightgbm_tpu/serve/server.py",
     "cls": "PredictionServer", "attr": "_lock", "kind": "lock",
     "guards": ("_closed", "_n_submitted", "_n_resolved", "_n_failed",
                "_n_batches", "_n_rows", "_n_padded", "_latency")},
    # -- elastic coordinator + client ----------------------------------
    {"name": "elastic_coord", "module": "lightgbm_tpu/parallel/elastic.py",
     "cls": "ElasticCoordinator", "attr": "_cv", "kind": "condition",
     "guards": ("_members", "_generation", "_join_seq", "_rounds",
                "_reads", "_touch", "_arrivals", "_round_sites",
                "_gauge_ranks", "_deadline_hint", "_stop"),
     # "Caller holds _cv" helpers (documented in their docstrings)
     "assume_held": ("_bump", "_ranks", "_view")},
    {"name": "elastic_client", "module": "lightgbm_tpu/parallel/elastic.py",
     "cls": "ElasticClient", "attr": "_state_lock", "kind": "lock",
     "guards": ("_seen_generation",)},
    # -- fault harness + log dedupe (leaf utility locks) ---------------
    {"name": "faults", "module": "lightgbm_tpu/utils/faults.py",
     "cls": None, "attr": "_lock", "kind": "lock",
     "guards": ("_arms", "_fired", "_calls", "_env_loaded"),
     "assume_held": ("_load_env",)},
    {"name": "log_once", "module": "lightgbm_tpu/utils/log.py",
     "cls": None, "attr": "_once_lock", "kind": "lock",
     "guards": ("_once_seen",)},
)

# ---------------------------------------------------------------------------
# the permitted nesting DAG: (outer, inner).  Nesting along any DAG
# path is legal; an acquisition pair with no path is CON002.
# ---------------------------------------------------------------------------
ORDER: Tuple[Tuple[str, str], ...] = (
    # telemetry mirrors every update into the sink while holding its
    # own lock; MetricsRegistry's lock is the declared leaf under it
    ("telemetry", "metrics_registry"),
    # ops_plane.mount()/shutdown() construct/tear down the plane under
    # the module lock: OpsPlane.__init__ enables telemetry and flips
    # health; both inner locks nest under the mount lock
    ("ops_plane", "telemetry"),
    ("ops_plane", "health"),
    # a failed mount logs the degradation while still under the module
    # lock; log_once's dedupe lock is a leaf
    ("ops_plane", "log_once"),
    # health._set_active holds the (reentrant) health lock through
    # _transition, whose tail publishes the section via telemetry
    ("health", "telemetry"),
    # the coordinator emits telemetry/ledger lines and polls fault
    # flags from inside its condition variable (monitor + op handlers)
    ("elastic_coord", "telemetry"),
    ("elastic_coord", "fleet_ledger"),
    ("elastic_coord", "faults"),
    ("elastic_coord", "log_once"),
    # every wrapped lock may report wait/hold samples into the contract
    # graph; the graph lock is a declared leaf under all of them
    ("telemetry", "lock_contract"),
    ("metrics_registry", "lock_contract"),
    ("elastic_coord", "lock_contract"),
)

# ---------------------------------------------------------------------------
# user-supplied callback seams (CON005)
# ---------------------------------------------------------------------------
CALLBACKS: Tuple[Dict, ...] = (
    # telemetry.set_sink installs an arbitrary object whose methods run
    # under the telemetry lock.  Safe ONLY because the one sanctioned
    # sink (MetricsRegistry) takes nothing but its declared-leaf lock;
    # tests/test_lock_contract.py pins the re-entrancy contract.
    {"module": "lightgbm_tpu/obs/telemetry.py", "name": "sink",
     "safe": "MetricsRegistry methods take only the declared-leaf "
             "metrics_registry lock (ORDER edge telemetry -> "
             "metrics_registry); re-entrancy pinned by "
             "tests/test_lock_contract.py"},
)
