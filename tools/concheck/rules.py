"""concheck rules: CON000-CON006 — thread & lock discipline, statically.

The analyzer is name-based and declaration-driven, same philosophy as
the other four walls (tpulint/spmdcheck/memcheck/detcheck): coarse
resolution, a declarative registry as ground truth
(``lock_registry.py``), and the rare over-taint handled by an inline
``# concheck: disable=CONxxx -- why`` with its justification, never by
a baseline entry.

Machinery shared per run (one AST parse via ``tools/analysis_core``):

* **Lock discovery** — structural (``X = threading.Lock()`` /
  ``self._cv = Condition()`` / the ``named_lock`` contract wrappers)
  merged with the central registry and in-file ``CONCHECK_*``
  declarations.  A ``with <lock>:`` resolves through the owning
  module + enclosing class.
* **Thread reachability** — roots are functions passed as
  ``Thread(target=...)`` plus the stdlib server callbacks that run on
  connection threads (``handle``/``do_GET``/``do_POST``); propagation
  rides the same name-based call-graph idea as
  ``tools/tpulint/callgraph.py``.
* **Lock closure** — which registered locks a call may acquire,
  transitively, with a stop-list of names too generic to resolve
  (``close``, ``run``, ...) pruned from *attribute* calls only; bare
  and ``self.``-method calls always propagate.

Rules:

* **CON000** — registry inconsistency: a declared lock whose module or
  attribute does not exist, an ORDER edge naming an unknown lock, or a
  cyclic declared DAG.
* **CON001** — a registered guarded name written from a
  thread-reachable function without its lock held.
* **CON002** — nested lock acquisition (lexical, or via a call's lock
  closure) whose (outer, inner) pair has no path in the declared DAG;
  re-entry is allowed for rlocks/conditions only.  Both acquisition
  sites are named.
* **CON003** — a blocking call (socket recv/accept, subprocess, jax
  ``block_until_ready``, ``sleep``, or ``wait``/``join``/``result``
  with no timeout) while a lock is held.  ``wait()`` on the held
  condition itself is the one exemption — that's what conditions do.
* **CON004** — a started ``threading.Thread`` with no reachable
  stop/join path (non-daemon: exit-hang; daemon: leak).
* **CON005** — a user-supplied callback/sink invoked under a held lock
  without a declared-safe justification (the ``telemetry.set_sink``
  re-entrancy seam).
* **CON006** — check-then-act: a guarded flag read in an ``if`` test
  outside its lock deciding a write to state of the same lock that is
  also unlocked.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis_core import FileInfo, Finding

from . import lock_registry

RULE_TITLES = {
    "CON000": "lock registry inconsistency",
    "CON001": "guarded state written without its lock",
    "CON002": "lock nesting outside the declared DAG",
    "CON003": "blocking call while holding a lock",
    "CON004": "thread started without a stop/join path",
    "CON005": "callback invoked under a held lock",
    "CON006": "check-then-act on a guarded flag outside its lock",
}

# lock constructors recognized structurally (stdlib + the runtime
# contract wrappers + the lazy factory utils modules use)
_LOCK_CTORS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "named_lock": "lock", "named_rlock": "rlock",
    "named_condition": "condition",
    "ContractLock": "lock", "ContractRLock": "rlock",
    "ContractCondition": "condition",
    "_named_lock": "lock", "_named_rlock": "rlock",
    "_named_condition": "condition",
}

# stdlib socket-server / http-server callbacks that run on connection
# threads: thread roots even though no Thread(target=...) names them
_THREAD_ENTRY_NAMES = {"handle", "do_GET", "do_POST"}

# attribute-call names too generic for name-based lock-closure
# propagation (a `x.close()` must not drag every `close` method's
# locks into the caller's nesting edges).  Bare-name and self-method
# calls are never pruned.
_NOISY_ATTR_CALLS = {
    "close", "get", "put", "read", "write", "run", "start", "stop",
    "join", "wait", "set", "clear", "update", "append", "pop", "add",
    "send", "recv", "open", "flush", "shutdown", "release", "acquire",
    "items", "values", "keys", "copy", "encode", "decode", "strip",
    "split", "mark", "observe", "state", "reset",
}

_ALWAYS_BLOCKING = {"recv", "recvfrom", "recv_into", "accept", "select",
                    "block_until_ready", "sleep"}
_SUBPROCESS_CALLS = {"Popen", "check_call", "check_output", "call"}
_TIMEOUT_BLOCKING = {"wait", "join", "result"}


# ---------------------------------------------------------------------------
# declarations
# ---------------------------------------------------------------------------
@dataclass
class LockDecl:
    name: str                       # registry-wide id
    attr: str                       # variable holding the lock
    cls: Optional[str]              # owning class (None = module level)
    kind: str                       # lock | rlock | condition
    guards: frozenset = frozenset()
    assume_held: frozenset = frozenset()
    declared: bool = False          # registry/in-file vs structural-only
    line: int = 0                   # assignment site (structural)


@dataclass
class ConFunc:
    fi: FileInfo
    node: ast.AST
    name: str
    qual: str                       # "<rel>::dotted.path"
    cls: Optional[str]              # innermost enclosing class
    called_bare: Set[str] = field(default_factory=set)
    called_attr: Set[str] = field(default_factory=set)

    @property
    def calls_for_reach(self) -> Set[str]:
        return self.called_bare | self.called_attr

    @property
    def calls_for_locks(self) -> Set[str]:
        return self.called_bare | (self.called_attr - _NOISY_ATTR_CALLS)


@dataclass
class ConContext:
    root: str
    files: List[FileInfo]
    by_rel: Dict[str, FileInfo]
    project_rules: bool
    funcs: Dict[str, ConFunc] = field(default_factory=dict)
    by_name: Dict[str, List[ConFunc]] = field(default_factory=dict)
    thread_reachable: Set[str] = field(default_factory=set)
    decls: Dict[str, List[LockDecl]] = field(default_factory=dict)
    callbacks: Dict[str, Dict[str, Optional[str]]] = field(
        default_factory=dict)
    order_edges: Set[Tuple[str, str]] = field(default_factory=set)
    order_reach: Dict[str, Set[str]] = field(default_factory=dict)
    fn_locks: Dict[str, Set[str]] = field(default_factory=dict)
    fn_locks_reach: Dict[str, Set[str]] = field(default_factory=dict)


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError, TypeError):
        return None


def _module_matches(rel: str, decl_module: str) -> bool:
    return rel == decl_module or rel.endswith("/" + decl_module)


# -- collection -----------------------------------------------------------
def _collect_module(fi: FileInfo, ctx: ConContext) -> None:
    """One walk: functions (with enclosing class), structural locks,
    in-file declarations."""
    structural: Dict[Tuple[Optional[str], str], Tuple[str, int]] = {}

    def note_lock(cls: Optional[str], attr: str, value: ast.AST,
                  line: int) -> None:
        if not isinstance(value, ast.Call):
            return
        kind = _LOCK_CTORS.get(_callee_name(value.func) or "")
        if kind is not None:
            structural.setdefault((cls, attr), (kind, line))

    def visit(node: ast.AST, cls: Optional[str], prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                func = ConFunc(fi=fi, node=child, name=child.name,
                               qual=f"{fi.rel}::{qual}", cls=cls)
                for sub in ast.walk(child):
                    if isinstance(sub, ast.Call):
                        if isinstance(sub.func, ast.Name):
                            func.called_bare.add(sub.func.id)
                        elif isinstance(sub.func, ast.Attribute):
                            base = sub.func.value
                            if (isinstance(base, ast.Name)
                                    and base.id in ("self", "cls")):
                                func.called_bare.add(sub.func.attr)
                            else:
                                func.called_attr.add(sub.func.attr)
                    elif isinstance(sub, ast.Assign):
                        for tgt in sub.targets:
                            if (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                note_lock(cls, tgt.attr, sub.value,
                                          sub.lineno)
                ctx.funcs[func.qual] = func
                ctx.by_name.setdefault(func.name, []).append(func)
                visit(child, cls, qual)
            elif isinstance(child, ast.ClassDef):
                visit(child, child.name, prefix)
            else:
                if isinstance(child, ast.Assign) and cls is None \
                        and not prefix:
                    for tgt in child.targets:
                        if isinstance(tgt, ast.Name):
                            note_lock(None, tgt.id, child.value,
                                      child.lineno)
                visit(child, cls, prefix)

    visit(fi.tree, None, "")

    # merge: central registry > in-file CONCHECK_* > structural
    decls: Dict[Tuple[Optional[str], str], LockDecl] = {}
    for (cls, attr), (kind, line) in structural.items():
        decls[(cls, attr)] = LockDecl(
            name=f"{fi.basename}:{attr}", attr=attr, cls=cls, kind=kind,
            line=line)

    infile = _infile_decls(fi)
    for (cls, attr), (guards, assume) in infile["locks"].items():
        d = decls.get((cls, attr))
        name = f"{fi.basename}:{attr}"
        if d is None:
            d = decls[(cls, attr)] = LockDecl(
                name=name, attr=attr, cls=cls, kind="lock")
        d.name = name
        d.guards = frozenset(guards)
        d.assume_held = frozenset(assume)
        d.declared = True
    for outer, inner in infile["order"]:
        ctx.order_edges.add((f"{fi.basename}:{outer}",
                             f"{fi.basename}:{inner}"))
    if infile["callbacks"]:
        ctx.callbacks.setdefault(fi.rel, {}).update(infile["callbacks"])

    for entry in lock_registry.LOCKS:
        if not _module_matches(fi.rel, entry["module"]):
            continue
        key = (entry.get("cls"), entry["attr"])
        d = decls.get(key)
        if d is None:
            d = decls[key] = LockDecl(
                name=entry["name"], attr=entry["attr"],
                cls=entry.get("cls"), kind=entry.get("kind", "lock"))
        d.name = entry["name"]
        d.kind = entry.get("kind", d.kind)
        d.guards = frozenset(entry.get("guards", ()))
        d.assume_held = frozenset(entry.get("assume_held", ()))
        d.declared = True
    for entry in lock_registry.CALLBACKS:
        if _module_matches(fi.rel, entry["module"]):
            ctx.callbacks.setdefault(fi.rel, {})[entry["name"]] = \
                entry.get("safe")

    ctx.decls[fi.rel] = list(decls.values())


def _infile_decls(fi: FileInfo) -> Dict:
    out = {"locks": {}, "order": [], "assume": set(), "callbacks": {}}
    for node in fi.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        tgt = node.targets[0].id
        val = _literal(node.value)
        if val is None:
            continue
        if tgt == "CONCHECK_LOCKS" and isinstance(val, dict):
            for key, guards in val.items():
                cls, _, attr = str(key).rpartition(".")
                out["locks"][(cls or None, attr)] = (
                    tuple(guards), ())
        elif tgt == "CONCHECK_ORDER":
            out["order"] = [tuple(p) for p in val if len(tuple(p)) == 2]
        elif tgt == "CONCHECK_ASSUME_HELD":
            out["assume"] = set(val)
        elif tgt == "CONCHECK_CALLBACKS":
            if isinstance(val, dict):
                out["callbacks"] = {str(k): v for k, v in val.items()}
            else:
                out["callbacks"] = {str(v): None for v in val}
    if out["assume"]:
        out["locks"] = {
            k: (guards, tuple(out["assume"]))
            for k, (guards, _) in out["locks"].items()}
    return out


# -- resolution helpers ---------------------------------------------------
def _resolve_lock(ctx: ConContext, fi: FileInfo, cls: Optional[str],
                  expr: ast.AST) -> Optional[LockDecl]:
    """The LockDecl a ``with <expr>:`` / ``<expr>.wait()`` refers to."""
    decls = ctx.decls.get(fi.rel, ())
    if isinstance(expr, ast.Name):
        for d in decls:
            if d.cls is None and d.attr == expr.id:
                return d
        return None
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        if expr.value.id in ("self", "cls"):
            best = None
            for d in decls:
                if d.attr != expr.attr:
                    continue
                if d.cls == cls:
                    return d
                if best is None:
                    best = d
            return best
        # `other._lock`: resolvable only when the attr is unambiguous
        cands = [d for d in decls if d.attr == expr.attr]
        return cands[0] if len(cands) == 1 else None
    return None


def _guard_decl(ctx: ConContext, fi: FileInfo, cls: Optional[str],
                name: str, is_self_attr: bool) -> Optional[LockDecl]:
    """The decl (if any) whose guards contain ``name``."""
    for d in ctx.decls.get(fi.rel, ()):
        if name not in d.guards:
            continue
        if is_self_attr:
            if d.cls is not None and (cls is None or d.cls == cls):
                return d
            if d.cls is None:
                # a module-global mutated through an alias is rare;
                # self-attrs prefer class-scoped decls
                continue
        else:
            if d.cls is None:
                return d
    return None


def _write_targets(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    if isinstance(node, ast.Delete):
        return list(node.targets)
    return []


def _base_written_name(tgt: ast.AST) -> Optional[Tuple[str, bool]]:
    """(name, is_self_attr) for the storage a write target mutates:
    ``x`` / ``x[k]`` -> ("x", False); ``self.y`` / ``self.y[k]`` ->
    ("y", True).  Tuple targets recurse in the caller."""
    while isinstance(tgt, ast.Subscript):
        tgt = tgt.value
    if isinstance(tgt, ast.Name):
        return tgt.id, False
    if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
            and tgt.value.id in ("self", "cls"):
        return tgt.attr, True
    return None


def _order_closure(edges: Set[Tuple[str, str]]) -> Dict[str, Set[str]]:
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    reach: Dict[str, Set[str]] = {}
    for start in adj:
        seen: Set[str] = set()
        work = list(adj.get(start, ()))
        while work:
            n = work.pop()
            if n in seen:
                continue
            seen.add(n)
            work.extend(adj.get(n, ()))
        reach[start] = seen
    return reach


# -- context --------------------------------------------------------------
def build_context(files: Sequence[FileInfo], root: str,
                  project_rules: bool) -> ConContext:
    ctx = ConContext(root=root, files=list(files),
                     by_rel={fi.rel: fi for fi in files},
                     project_rules=project_rules)
    for a, b in lock_registry.ORDER:
        ctx.order_edges.add((a, b))
    for fi in files:
        _collect_module(fi, ctx)
    ctx.order_reach = _order_closure(ctx.order_edges)

    # thread roots: Thread(target=...) / Timer(..., f) + server entries
    root_names: Set[str] = set(_THREAD_ENTRY_NAMES)
    for fi in files:
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            if _callee_name(node.func) not in ("Thread", "Timer"):
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    name = _callee_name(kw.value)
                    if name:
                        root_names.add(name)
    work = [f.qual for f in ctx.funcs.values() if f.name in root_names]
    while work:
        q = work.pop()
        if q in ctx.thread_reachable:
            continue
        ctx.thread_reachable.add(q)
        for callee in ctx.funcs[q].calls_for_reach:
            for f in ctx.by_name.get(callee, ()):
                if f.qual not in ctx.thread_reachable:
                    work.append(f.qual)

    # per-function directly-acquired locks, then the transitive closure
    for func in ctx.funcs.values():
        acquired: Set[str] = set()
        for sub in ast.walk(func.node):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    d = _resolve_lock(ctx, func.fi, func.cls,
                                      item.context_expr)
                    if d is not None:
                        acquired.add(d.name)
        ctx.fn_locks[func.qual] = acquired
    for func in ctx.funcs.values():
        seen: Set[str] = set(ctx.fn_locks[func.qual])
        visited = {func.qual}
        work = [c for c in func.calls_for_locks]
        while work:
            callee = work.pop()
            for f in ctx.by_name.get(callee, ()):
                if f.qual in visited:
                    continue
                visited.add(f.qual)
                seen |= ctx.fn_locks[f.qual]
                work.extend(f.calls_for_locks)
        ctx.fn_locks_reach[func.qual] = seen
    return ctx


# ---------------------------------------------------------------------------
# per-function walk: CON001/CON002/CON003/CON005/CON006
# ---------------------------------------------------------------------------
def _edge_ok(ctx: ConContext, outer: LockDecl, inner_name: str,
             inner_kind: Optional[str]) -> bool:
    if outer.name == inner_name:
        return (inner_kind or "lock") in ("rlock", "condition")
    if (outer.name, inner_name) in ctx.order_edges:
        return True
    return inner_name in ctx.order_reach.get(outer.name, ())


def _is_blocking(call: ast.Call, held: List[Tuple[LockDecl, int]],
                 ctx: ConContext, fi: FileInfo, cls: Optional[str]
                 ) -> Optional[str]:
    name = _callee_name(call.func)
    if name is None:
        return None
    dotted = _dotted(call.func) if isinstance(call.func,
                                              ast.Attribute) else name
    if name in _ALWAYS_BLOCKING:
        return f"{dotted}()"
    if name in _SUBPROCESS_CALLS or dotted.startswith("subprocess."):
        return f"{dotted}()"
    if name in _TIMEOUT_BLOCKING:
        has_timeout = bool(call.args) or any(
            kw.arg == "timeout" for kw in call.keywords)
        if has_timeout:
            return None
        # `held_cv.wait()` releases the held condition: exempt
        if name == "wait" and isinstance(call.func, ast.Attribute):
            d = _resolve_lock(ctx, fi, cls, call.func.value)
            if d is not None and held and d.name == held[-1][0].name \
                    and d.kind == "condition":
                return None
        return f"{dotted}() with no timeout"
    return None


def _scan_function(func: ConFunc, ctx: ConContext,
                   out: List[Finding]) -> None:
    fi = func.fi
    reachable = func.qual in ctx.thread_reachable
    cbmap = ctx.callbacks.get(fi.rel, {})
    reported_edges: Set[Tuple[str, str, str]] = set()

    def check_write(node: ast.AST, held: List[Tuple[LockDecl, int]],
                    quiet: bool = False) -> Optional[LockDecl]:
        hit = None
        for tgt in _write_targets(node):
            tgts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) \
                else [tgt]
            for t in tgts:
                based = _base_written_name(t)
                if based is None:
                    continue
                name, is_self = based
                d = _guard_decl(ctx, fi, func.cls, name, is_self)
                if d is None:
                    continue
                hit = d
                if quiet:
                    continue
                if any(h.name == d.name for h, _ in held):
                    continue
                if func.name in d.assume_held or func.name == "__init__":
                    continue
                if not reachable:
                    continue
                out.append(Finding(
                    fi.rel, node.lineno, "CON001",
                    f"'{name}' is registered as guarded by lock "
                    f"'{d.name}' but is written here without it; this "
                    f"function is reachable from a thread entry point. "
                    f"Hold the lock, or move the name out of the "
                    f"registry entry with a why."))
        return hit

    def check_call(call: ast.Call,
                   held: List[Tuple[LockDecl, int]]) -> None:
        if not held:
            return
        outer, outer_line = held[-1]
        blocking = _is_blocking(call, held, ctx, fi, func.cls)
        if blocking is not None:
            out.append(Finding(
                fi.rel, call.lineno, "CON003",
                f"blocking call {blocking} while holding lock "
                f"'{outer.name}' (acquired line {outer_line}): every "
                f"other acquirer stalls behind this wait.  Move the "
                f"call outside the critical section or bound it with "
                f"a timeout."))
        # CON005: user-supplied callback under a held lock
        cb_name = None
        if isinstance(call.func, ast.Name) and call.func.id in cbmap:
            cb_name = call.func.id
        elif isinstance(call.func, ast.Attribute):
            base = call.func.value
            if isinstance(base, ast.Name) and base.id in cbmap:
                cb_name = base.id
            elif isinstance(base, ast.Name) and base.id in ("self", "cls") \
                    and call.func.attr in cbmap:
                cb_name = call.func.attr
            if call.func.attr in cbmap and cb_name is None \
                    and isinstance(base, ast.Name):
                cb_name = call.func.attr
        if cb_name is not None and cbmap.get(cb_name) is None:
            out.append(Finding(
                fi.rel, call.lineno, "CON005",
                f"callback '{cb_name}' invoked while holding lock "
                f"'{outer.name}' (acquired line {outer_line}): a "
                f"callback that re-enters this module re-acquires the "
                f"lock and deadlocks (rlock) or self-deadlocks (lock). "
                f"Invoke it outside the lock, or declare it safe in "
                f"the registry with a leaf-lock argument."))
        # CON002 via the callee's transitive lock set
        callee = _callee_name(call.func)
        if callee is None:
            return
        attr_style = isinstance(call.func, ast.Attribute) and not (
            isinstance(call.func.value, ast.Name)
            and call.func.value.id in ("self", "cls"))
        if attr_style and callee in _NOISY_ATTR_CALLS:
            return
        inner: Set[str] = set()
        kinds: Dict[str, str] = {}
        for f in ctx.by_name.get(callee, ()):
            inner |= ctx.fn_locks_reach.get(f.qual, set())
        for rel_decls in ctx.decls.values():
            for d in rel_decls:
                kinds.setdefault(d.name, d.kind)
        for lock_name in sorted(inner):
            if _edge_ok(ctx, outer, lock_name, kinds.get(lock_name)):
                continue
            key = (outer.name, lock_name, callee)
            if key in reported_edges:
                continue
            reported_edges.add(key)
            out.append(Finding(
                fi.rel, call.lineno, "CON002",
                f"call to {callee}() may acquire lock '{lock_name}' "
                f"while holding '{outer.name}' (acquired line "
                f"{outer_line}), an edge absent from the declared "
                f"lock-order DAG — a concurrent acquirer in the "
                f"opposite order deadlocks.  Declare the edge in "
                f"lock_registry.ORDER or move the call outside the "
                f"lock."))

    def check_if(node: ast.If, held: List[Tuple[LockDecl, int]]) -> None:
        held_names = {h.name for h, _ in held}
        read: Optional[Tuple[str, LockDecl]] = None
        for sub in ast.walk(node.test):
            based = None
            if isinstance(sub, ast.Name):
                based = (sub.id, False)
            elif isinstance(sub, ast.Attribute) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in ("self", "cls"):
                based = (sub.attr, True)
            if based is None:
                continue
            d = _guard_decl(ctx, fi, func.cls, based[0], based[1])
            if d is not None and d.name not in held_names:
                read = (based[0], d)
                break
        if read is None:
            return
        flag, d = read
        if func.name in d.assume_held or func.name == "__init__":
            return
        # an unlocked write to the same lock's state anywhere in the
        # If body/orelse (a write under the lock is double-checked
        # locking, which is fine — the decision is re-validated)
        for body in (node.body, node.orelse):
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef, ast.With)):
                        continue
                    if not _write_targets(sub):
                        continue
                    # skip writes nested under a With on d's lock
                    if _under_lock_with(stmt, sub, ctx, fi, func.cls,
                                        d.name):
                        continue
                    for tgt in _write_targets(sub):
                        based = _base_written_name(tgt)
                        if based is None:
                            continue
                        dd = _guard_decl(ctx, fi, func.cls, based[0],
                                         based[1])
                        if dd is not None and dd.name == d.name:
                            out.append(Finding(
                                fi.rel, node.lineno, "CON006",
                                f"check-then-act: '{flag}' (guarded by "
                                f"lock '{d.name}') is tested here "
                                f"without the lock and '{based[0]}' is "
                                f"then written at line {sub.lineno}, "
                                f"also unlocked — two threads can both "
                                f"pass the test.  Take the lock around "
                                f"the test AND the act."))
                            return

    def walk(stmts: Sequence[ast.AST],
             held: List[Tuple[LockDecl, int]]) -> None:
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                # separate ConFunc / class scope
            if isinstance(node, ast.With):
                acquired: List[LockDecl] = []
                for item in node.items:
                    d = _resolve_lock(ctx, fi, func.cls,
                                      item.context_expr)
                    if d is None:
                        continue
                    if held:
                        outer, outer_line = held[-1]
                        if not _edge_ok(ctx, outer, d.name, d.kind):
                            key = (outer.name, d.name, "")
                            if key not in reported_edges:
                                reported_edges.add(key)
                                out.append(Finding(
                                    fi.rel, node.lineno, "CON002",
                                    f"lock '{d.name}' acquired here "
                                    f"while holding '{outer.name}' "
                                    f"(acquired line {outer_line}): "
                                    f"this nesting edge is absent "
                                    f"from the declared lock-order "
                                    f"DAG — the reverse order "
                                    f"elsewhere deadlocks.  Declare "
                                    f"it in lock_registry.ORDER or "
                                    f"restructure."))
                    held.append((d, node.lineno))
                    acquired.append(d)
                walk(node.body, held)
                for _ in acquired:
                    held.pop()
                continue
            if isinstance(node, ast.If):
                check_if(node, held)
            check_write(node, held)
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, (ast.stmt,)):
                    continue            # handled by the stmt recursion
                for call in ast.walk(sub):
                    if isinstance(call, ast.Call):
                        check_call(call, held)
            body_attrs = [getattr(node, f, []) for f in
                          ("body", "orelse", "finalbody", "handlers")]
            for blk in body_attrs:
                if blk and isinstance(blk[0], ast.ExceptHandler):
                    for h in blk:
                        walk(h.body, held)
                elif blk:
                    walk(blk, held)

    body = getattr(func.node, "body", [])
    walk(body, [])


def _under_lock_with(top: ast.AST, target: ast.AST, ctx: ConContext,
                     fi: FileInfo, cls: Optional[str],
                     lock_name: str) -> bool:
    """True when ``target`` sits under a ``with <lock_name>`` inside
    ``top`` (double-checked locking recognition for CON006)."""
    found = False

    def visit(node: ast.AST, locked: bool) -> None:
        nonlocal found
        if node is target and locked:
            found = True
            return
        now = locked
        if isinstance(node, ast.With):
            for item in node.items:
                d = _resolve_lock(ctx, fi, cls, item.context_expr)
                if d is not None and d.name == lock_name:
                    now = True
        for child in ast.iter_child_nodes(node):
            visit(child, now)

    visit(top, False)
    return found


# ---------------------------------------------------------------------------
# CON004: thread lifecycle (module-wide)
# ---------------------------------------------------------------------------
def rule_thread_lifecycle(fi: FileInfo, ctx: ConContext) -> List[Finding]:
    out: List[Finding] = []
    join_bases: Set[str] = set()
    start_bases: Set[str] = set()
    joined_containers: Set[str] = set()
    containers: Dict[str, Set[str]] = {}    # container attr -> member names
    thread_calls: List[Tuple[ast.Call, ast.AST]] = []   # (ctor, parent)
    # enclosing `for <var> in <container>` frames, innermost last
    for_stack: List[Tuple[str, str]] = []

    # ONE parent-tracking traversal gathers everything the verdict pass
    # needs (the naive shape — a parents map plus a full ast.walk per
    # fact plus a nested walk per For — dominated the whole analyzer)
    def scan(node: ast.AST, parent: Optional[ast.AST]) -> None:
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                base = node.func.value
                base_name = None
                if isinstance(base, ast.Name):
                    base_name = base.id
                elif isinstance(base, ast.Attribute):
                    base_name = base.attr
                if base_name:
                    if node.func.attr == "join":
                        join_bases.add(base_name)
                        if isinstance(base, ast.Name):
                            for v, it_name in for_stack:
                                if v == base.id:
                                    joined_containers.add(it_name)
                    elif node.func.attr == "start":
                        start_bases.add(base_name)
                    elif node.func.attr == "append" and node.args:
                        member = node.args[0]
                        if isinstance(member, ast.Name):
                            containers.setdefault(base_name, set()).add(
                                member.id)
            if _callee_name(node.func) in ("Thread", "Timer"):
                thread_calls.append((node, parent))
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, (ast.List, ast.Tuple)):
            names = {e.id for e in node.value.elts
                     if isinstance(e, ast.Name)}
            if names:
                for tgt in node.targets:
                    based = _base_written_name(tgt)
                    if based is not None:
                        containers.setdefault(based[0], set()).update(
                            names)
        pushed = False
        if isinstance(node, ast.For) and isinstance(node.target,
                                                    ast.Name):
            it = node.iter
            it_name = None
            if isinstance(it, ast.Name):
                it_name = it.id
            elif isinstance(it, ast.Attribute):
                it_name = it.attr
            if it_name:
                for_stack.append((node.target.id, it_name))
                pushed = True
        for child in ast.iter_child_nodes(node):
            scan(child, node)
        if pushed:
            for_stack.pop()

    scan(fi.tree, None)

    def joined(binding: str) -> bool:
        if binding in join_bases:
            return True
        for cont, members in containers.items():
            if binding in members and cont in joined_containers:
                return True
        return False

    for node, parent in thread_calls:
        daemon = False
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        binding: Optional[str] = None
        inline_start = (isinstance(parent, ast.Attribute)
                        and parent.attr == "start")
        if isinstance(parent, ast.Assign):
            based = _base_written_name(parent.targets[0])
            if based is not None:
                binding = based[0]
        why = ("daemon leak" if daemon
               else "a non-daemon thread with no join path delays "
                    "interpreter exit indefinitely")
        if inline_start:
            out.append(Finding(
                fi.rel, node.lineno, "CON004",
                f"Thread started inline with no handle: nothing can "
                f"ever stop or join it ({why}).  Keep the handle and "
                f"give it a stop + join(timeout) path."))
            continue
        if binding is None:
            continue                    # passed straight somewhere: rare,
            #                             the container rules can't see it
        if binding not in start_bases:
            continue                    # never started
        if joined(binding):
            continue
        why2 = ("a daemon with no stop/join path leaks until process "
                "exit" if daemon
                else "a non-daemon thread with no join path hangs "
                     "interpreter exit")
        out.append(Finding(
            fi.rel, node.lineno, "CON004",
            f"thread bound to '{binding}' is started but no join path "
            f"exists in this module ({why2}).  Add a stop + "
            f"join(timeout) path (the bounded-shutdown contract)."))
    return out


# ---------------------------------------------------------------------------
# file rules
# ---------------------------------------------------------------------------
def rule_function_walks(fi: FileInfo, ctx: ConContext) -> List[Finding]:
    out: List[Finding] = []
    for func in ctx.funcs.values():
        if func.fi.rel == fi.rel:
            _scan_function(func, ctx, out)
    return out


FILE_RULES = (rule_function_walks, rule_thread_lifecycle)


# ---------------------------------------------------------------------------
# project rules: CON000 registry soundness
# ---------------------------------------------------------------------------
def rule_registry_sound(ctx: ConContext) -> List[Finding]:
    out: List[Finding] = []
    names: Set[str] = set()
    for entry in lock_registry.LOCKS:
        name = entry["name"]
        if name in names:
            out.append(Finding(
                entry["module"], 1, "CON000",
                f"duplicate lock name '{name}' in lock_registry.LOCKS"))
        names.add(name)
        matches = [fi for fi in ctx.files
                   if _module_matches(fi.rel, entry["module"])]
        if not matches:
            out.append(Finding(
                entry["module"], 1, "CON000",
                f"lock '{name}' declares module '{entry['module']}' "
                f"which is not among the analyzed files"))
            continue
        fi = matches[0]
        found = any(
            d.attr == entry["attr"] and d.cls == entry.get("cls")
            and d.line
            for d in ctx.decls.get(fi.rel, ()))
        if not found:
            out.append(Finding(
                fi.rel, 1, "CON000",
                f"lock '{name}' declares attribute "
                f"'{entry.get('cls') or '<module>'}.{entry['attr']}' "
                f"but no lock construction for it was found"))
    for a, b in lock_registry.ORDER:
        for n in (a, b):
            if n not in names:
                out.append(Finding(
                    "tools/concheck/lock_registry.py", 1, "CON000",
                    f"ORDER edge ({a!r}, {b!r}) references unknown "
                    f"lock '{n}'"))
    # the declared DAG must actually be a DAG
    adj: Dict[str, Set[str]] = {}
    for a, b in lock_registry.ORDER:
        adj.setdefault(a, set()).add(b)
    state: Dict[str, int] = {}

    def cyclic(n: str, path: List[str]) -> Optional[List[str]]:
        state[n] = 1
        for m in adj.get(n, ()):
            if state.get(m, 0) == 1:
                return path + [n, m]
            if state.get(m, 0) == 0:
                got = cyclic(m, path + [n])
                if got:
                    return got
        state[n] = 2
        return None

    for n in list(adj):
        if state.get(n, 0) == 0:
            cycle = cyclic(n, [])
            if cycle:
                out.append(Finding(
                    "tools/concheck/lock_registry.py", 1, "CON000",
                    f"declared lock-order DAG contains a cycle: "
                    f"{' -> '.join(cycle)}"))
                break
    return out


PROJECT_RULES = (rule_registry_sound,)


# ---------------------------------------------------------------------------
# the lock-graph view (CLI --lockgraph)
# ---------------------------------------------------------------------------
def render_lockgraph(ctx: ConContext) -> str:
    lines: List[str] = ["# concheck lock registry", ""]
    for entry in lock_registry.LOCKS:
        owner = entry.get("cls") or "<module>"
        lines.append(f"{entry['name']:18s} {entry.get('kind', 'lock'):10s} "
                     f"{entry['module']} {owner}.{entry['attr']}")
        guards = ", ".join(entry.get("guards", ())) or "-"
        lines.append(f"{'':18s} guards: {guards}")
    lines.append("")
    lines.append("# declared order (outer -> inner)")
    for a, b in lock_registry.ORDER:
        lines.append(f"{a} -> {b}")
    threads = sorted(q for q in ctx.thread_reachable)
    lines.append("")
    lines.append(f"# thread-reachable functions: {len(threads)}")
    return "\n".join(lines) + "\n"
