"""concheck — thread & lock discipline analyzer.

The fifth static gate (after tpulint, spmdcheck, memcheck, detcheck),
aimed at the hazards a threaded substrate breeds: data races on
guarded state, lock-order inversions, blocking calls inside critical
sections, leaked threads, callback re-entrancy, and check-then-act
races.  Rules CON000-CON006 (see ``rules.py``) run as a tier-1 gate
via ``tests/test_concheck.py`` / ``python -m tools.check`` and by
hand::

    python -m tools.concheck [--update-baseline] [--lockgraph] [paths...]

Shares the analyzer plumbing in ``tools/analysis_core.py`` (one AST
parse per file per process, ``# concheck: disable=CONxxx -- why``
suppressions, content-keyed baseline — committed EMPTY).  The
declarative contract lives in ``lock_registry.py`` (lock → guarded
names; the permitted nesting DAG; the callback seams).  The RUNTIME
half is the lock-order contract (``lightgbm_tpu/obs/lock_contract.py``,
``LGBM_TPU_LOCK_CONTRACT=1``) and the interleaving fuzzer
(``tools/interleave.py``); this package only analyzes source.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from tools.analysis_core import (FileInfo, Finding, discover_files,
                                 load_baseline, new_findings, suppressed,
                                 write_baseline)

from .rules import (FILE_RULES, PROJECT_RULES, RULE_TITLES, build_context,
                    render_lockgraph)

BASELINE_DEFAULT = os.path.join("tools", "concheck", "baseline.json")

__all__ = [
    "run_concheck", "Finding", "RULE_TITLES", "load_baseline",
    "write_baseline", "new_findings", "BASELINE_DEFAULT",
]


def run_concheck(paths: Sequence[str] = ("lightgbm_tpu",),
                 root: Optional[str] = None,
                 project_rules: bool = True,
                 ) -> Tuple[List[Finding], Dict[str, FileInfo]]:
    """Analyze ``paths``; returns (findings sorted by location, FileInfo
    by relative path).  Inline suppressions applied; the baseline is NOT
    — callers diff via :func:`new_findings` (same contract as the other
    four analyzers).  ``project_rules=False`` skips the registry-
    soundness project rule for fixture runs."""
    root = os.path.abspath(root or os.getcwd())
    files = discover_files(paths, root)
    ctx = build_context(files, root, project_rules=project_rules)
    findings: List[Finding] = []
    for fi in files:
        for rule in FILE_RULES:
            for f in rule(fi, ctx):
                if not suppressed(fi, f):
                    findings.append(f)
    if project_rules:
        for rule in PROJECT_RULES:
            findings.extend(rule(ctx))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings, ctx.by_rel


def render_graph(paths: Sequence[str] = ("lightgbm_tpu",),
                 root: Optional[str] = None) -> str:
    """The ``--lockgraph`` CLI view: registry + declared order DAG."""
    root = os.path.abspath(root or os.getcwd())
    files = discover_files(paths, root)
    ctx = build_context(files, root, project_rules=False)
    return render_lockgraph(ctx)
