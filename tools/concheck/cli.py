"""concheck CLI: ``python -m tools.concheck [options] [paths...]``.

Exit codes mirror the other analyzers: 0 = clean vs baseline, 1 = new
findings, 2 = usage error.  Output is ``file:line: RULE message``.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import (BASELINE_DEFAULT, load_baseline, new_findings,
               render_graph, run_concheck, write_baseline)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.concheck",
        description="thread & lock discipline analyzer for lightgbm_tpu "
                    "(rules CON000-CON006; see README 'Static "
                    "analysis')")
    parser.add_argument("paths", nargs="*", default=["lightgbm_tpu"],
                        help="files/directories to analyze "
                             "(default: lightgbm_tpu)")
    parser.add_argument("--root", default=None,
                        help="project root (default: cwd)")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help=f"baseline file (default: {BASELINE_DEFAULT} "
                             f"under --root)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, pinned or not")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to pin the current "
                             "findings, then exit 0")
    parser.add_argument("--no-project-rules", action="store_true",
                        help="skip the registry-soundness project rule")
    parser.add_argument("--lockgraph", action="store_true",
                        help="dump the lock registry + declared order "
                             "DAG, then exit 0")
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root or os.getcwd())
    baseline_path = (os.path.abspath(args.baseline) if args.baseline
                     else os.path.join(root, BASELINE_DEFAULT))
    try:
        if args.lockgraph:
            sys.stdout.write(render_graph(args.paths or ["lightgbm_tpu"],
                                          root=root))
            return 0
        findings, by_rel = run_concheck(
            args.paths or ["lightgbm_tpu"], root=root,
            project_rules=not args.no_project_rules)
    except OSError as exc:
        print(f"concheck: {exc}", file=sys.stderr)
        return 2

    if args.update_baseline:
        write_baseline(baseline_path, findings, by_rel,
                       tool="tools.concheck")
        print(f"concheck: baseline updated with {len(findings)} "
              f"finding(s) at {os.path.relpath(baseline_path, root)}")
        return 0

    baseline = ({} if args.no_baseline
                else load_baseline(baseline_path))
    fresh = new_findings(findings, by_rel, baseline)
    for f in fresh:
        print(f.render())
    pinned = len(findings) - len(fresh)
    if fresh:
        print(f"concheck: {len(fresh)} new finding(s)"
              + (f" ({pinned} baselined)" if pinned else "")
              + "; fix them, suppress with justification "
                "(# concheck: disable=CONxxx -- why), or refresh the "
                "baseline with --update-baseline")
        return 1
    print(f"concheck: clean ({pinned} baselined finding(s), "
          f"{len(by_rel)} file(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
