"""GPU north-star arithmetic (VERDICT r4 #3) — every letter a number.

BASELINE.md's primary target: beat the reference's OpenCL GPU learner on
HIGGS wall-clock on a single v5e-8.  The reference never states the GPU
learner's HIGGS wall-clock in text (the chart is an image,
`docs/GPU-Performance.rst:164-166`); the only *numeric* speedup in its
docs is "over three times speedup" (`docs/GPU-Tutorial.rst:162`, Higgs on
a half-M60) and the qualitative bound "a *budget* GPU can still compete
and be faster than a 28-core Haswell server"
(`docs/GPU-Performance.rst:172`).  We adopt the AGGRESSIVE reading as the
target: **GPU target = 3.0x the 238.505 s / 22.0M row-iters/s CPU
baseline**, i.e. 66.1M row-iters/s — even though the tutorial's own CPU
was a 6-vCPU VM (so 3x that box is likely < 1x the 28-core box, making
3x a deliberately hard target).

This tool records, on the real chip:
  * measured dense MXU peak (int8 + bf16 matmul microbench),
  * per-wave histogram-kernel time at bench shapes -> MXU utilization,
  * warm end-to-end s/iteration at 1M rows (and 10.5M with FULL=1),
  * all-reduce bytes per tree for the 8-way data-parallel HIGGS config
    (HLO-measured on the virtual CPU mesh; byte volume is row-count
    independent: histograms are [A, F, B, 3]),
and derives: single-chip multiple Y, needed 8-chip scaling Z = X/Y, and
the projected 8-chip multiple from measured per-chip compute vs ICI
all-reduce time (worst case, no overlap).

Timing uses a device->host scalar fetch as the barrier (on tunneled
runtimes ``block_until_ready`` can return before execution finishes).

Run on TPU:  python tools/north_star.py        (writes tests/data/north_star.json)
             FULL=1 python tools/north_star.py (adds the 10.5M-row leg)
"""
import json
import os
import re
import subprocess
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

ARTIFACT = os.path.join(ROOT, "tests", "data", "north_star.json")

CPU_BASELINE_ROW_ITERS = 10.5e6 * 500 / 238.505     # 22.0M (Experiments.rst)
GPU_TARGET_MULTIPLE = 3.0                           # GPU-Tutorial.rst:162
# public v5e spec: 1600 Gbps interchip interconnect per chip; a ring
# all-reduce of S bytes on 8 chips moves ~2*S*(7/8) per chip -> we use
# an effective 100 GB/s unidirectional aggregate
ICI_EFFECTIVE_GBPS = 100.0


from bench import _sync                           # noqa: E402  (same
# tunneled-runtime device barrier: block_until_ready can return early)


def measured_peak():
    """Dense matmul microbench: the chip's achievable MAC rates.

    The K matmuls are DEPENDENCY-CHAINED inside one jitted fori_loop
    (``a <- cast(a @ w)``) so one dispatch covers the whole chain —
    per-dispatch tunnel latency (~5-10 ms on this runtime) would
    otherwise drown the measurement."""
    import jax
    import jax.numpy as jnp
    out = {}
    m = 8192
    for dtype, acc, name in ((jnp.int8, jnp.int32, "int8"),
                             (jnp.bfloat16, jnp.float32, "bf16")):
        a0 = jnp.ones((m, m), dtype)
        w = jnp.eye(m, dtype=dtype)

        def run(K):
            @jax.jit
            def chain(a, w):
                def body(s, _):
                    y = jax.lax.dot_general(
                        s, w, (((1,), (0,)), ((), ())),
                        preferred_element_type=acc)
                    # REAL dependency chain: the next step consumes the
                    # full product (w = identity keeps values bounded),
                    # so the dot cannot be hoisted as loop-invariant
                    return jnp.clip(y, -127, 127).astype(s.dtype), None
                s, _ = jax.lax.scan(body, a, None, length=K)
                return s
            _sync(chain(a0, w))
            t0 = time.time()
            _sync(chain(a0, w))
            return time.time() - t0

        # single-dispatch timing carries a ~100 ms tunnel round-trip on
        # this runtime: the (K2-K1) slope cancels it exactly
        k1, k2 = 8, 40
        dt = (run(k2) - run(k1)) / (k2 - k1)
        out[f"peak_{name}_tmacs"] = round(m * m * m / dt / 1e12, 1)
    return out


def wave_times(peak_tmacs, f=28, max_bin=63):
    """Histogram-kernel cost per wave by active-slot count, measured as
    the SLOPE between 1M and 4M rows (standalone dispatches carry ~5-10
    ms of tunnel latency each; the slope cancels every fixed cost, and
    matches the in-scan per-row cost observed in device traces)."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops import pallas_histogram as ph
    B = ph.bin_stride(max_bin)
    sizes = (1_000_000, 4_000_000)
    ms_at = {}
    for n in sizes:
        rng = np.random.RandomState(0)
        bins = rng.randint(0, max_bin + 1, size=(n, f)).astype(np.uint8)
        bt = jnp.asarray(ph.transpose_bins_host(bins))
        del bins
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        h = jnp.abs(jnp.asarray(rng.normal(size=n).astype(np.float32)))
        row_leaf = jnp.asarray(
            rng.randint(0, 255, size=bt.shape[1]).astype(np.int32))
        vals, scales = ph.pack_values_q(g, h, "int8h")
        for a in (1, 8, 32, 64, 128):
            act = jnp.arange(a, dtype=jnp.int32)
            out = ph.hist_active_pallas(bt, vals, row_leaf, act, scales,
                                        num_features=f, max_bins=max_bin,
                                        mode="int8h")
            _sync(out)
            reps = 10
            t0 = time.time()
            for _ in range(reps):
                out = ph.hist_active_pallas(bt, vals, row_leaf, act,
                                            scales, num_features=f,
                                            max_bins=max_bin, mode="int8h")
            _sync(out)
            ms_at[(a, n)] = (time.time() - t0) / reps * 1e3
        del bt, g, h, vals, row_leaf
        import gc
        gc.collect()
    rows = []
    for a in (1, 8, 32, 64, 128):
        slope_ns = ((ms_at[(a, sizes[1])] - ms_at[(a, sizes[0])]) * 1e6
                    / (sizes[1] - sizes[0]))
        cols = ph._col_layout(a, "int8h")[2]
        macs_row = f * B * cols
        tmacs = macs_row / max(slope_ns, 1e-9) / 1e3
        rows.append({"active": a, "ns_per_row": round(slope_ns, 2),
                     "dispatch_ms_1m": round(ms_at[(a, sizes[0])], 2),
                     "mxu_util_vs_measured_peak": round(
                         tmacs / peak_tmacs, 3)})
    return rows


def iter_time(n, iters=32, leaves=255, max_bin=63):
    """Warm end-to-end training s/iteration at the bench config."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.basic import Booster
    rng = np.random.RandomState(0)
    X = rng.normal(size=(n, 28)).astype(np.float32)
    y = (X[:, 0] * 2 + X[:, 1] - X[:, 2]
         + rng.normal(size=n) > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"max_bin": max_bin})
    ds.construct()
    del X
    params = {"objective": "binary", "num_leaves": leaves,
              "max_bin": max_bin, "learning_rate": 0.1,
              "min_data_in_leaf": 20, "verbose": -1}
    bst = Booster(params=params, train_set=ds)
    g = bst._gbdt
    bst.update()
    g.train_block(3 * iters)
    _sync(g.scores)

    def run(k):
        t0 = time.time()
        g.train_block(k)
        _sync(g.scores)
        return time.time() - t0

    # slope between two window lengths cancels the per-call tunnel
    # round-trip (~100 ms on this runtime)
    dt = (run(3 * iters) - run(iters)) / (2 * iters)
    del bst, ds, g
    import gc
    gc.collect()
    return dt


_DT = {"f64": 8, "f32": 4, "bf16": 2, "s32": 4, "u32": 4, "s8": 1,
       "u8": 1, "pred": 1, "s64": 8, "u64": 8, "f16": 2}


def _collective_bytes(txt):
    total = 0
    for m in re.finditer(
            r"=\s*(\([^)]*\)|\S+)\s+"
            r"(?:all-reduce|all-gather|reduce-scatter)(?:-start)?\(",
            txt):
        shapes = re.findall(r"(f64|f32|bf16|f16|s64|u64|s32|u32|s8|u8|pred)"
                            r"\[([\d,]*)\]", m.group(1))
        for dt, dims in shapes:
            elems = 1
            for d in dims.split(","):
                if d:
                    elems *= int(d)
            total += elems * _DT[dt]
    return total


def collective_bytes_per_tree():
    """All-reduce bytes for one 255-leaf data-parallel tree at the HIGGS
    bin/feature config, measured from compiled HLO on the virtual 8-CPU
    mesh (bytes are independent of row count: histogram grids are
    [A, F, B, 3])."""
    code = r"""
import sys, re
sys.path.insert(0, %r)
import numpy as np, jax, jax.numpy as jnp
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.io.device import to_device
from lightgbm_tpu.learner.serial import GrowthParams
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.parallel.learners import build_tree_distributed
from lightgbm_tpu.parallel.mesh import make_mesh
rng = np.random.RandomState(0)
n, f = 65536, 28
X = rng.normal(size=(n, f)).astype(np.float32)
ds = BinnedDataset.from_raw(X, Config.from_params({"max_bin": 63}))
dd = to_device(ds)
grad = jnp.asarray(rng.normal(size=n).astype(np.float32))
hess = jnp.ones(n) * 0.25
p = GrowthParams(num_leaves=255, split=SplitParams(
    min_data_in_leaf=20, min_sum_hessian_in_leaf=1e-3))
mesh = make_mesh(8)
fn = jax.jit(lambda g, h: build_tree_distributed(
    mesh, "data", "data", dd, g, h, p, hist_backend="scatter"))
txt = fn.lower(grad, hess).compile().as_text()
print("HLO_TEXT_BYTES", len(txt))
import json
sys.stdout.write("COLLECTIVE_HLO_START\n")
# emit only collective op lines to keep the pipe small
for line in txt.splitlines():
    if ("all-reduce" in line or "all-gather" in line
            or "reduce-scatter" in line):
        print(line)
print("COLLECTIVE_HLO_END")
""" % ROOT
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if "PYTHONPATH" in env:
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in env["PYTHONPATH"].split(os.pathsep)
            if p and ".axon_site" not in os.path.basename(p.rstrip("/")))
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=3600)
    if "COLLECTIVE_HLO_START" not in r.stdout:
        raise RuntimeError(f"collective probe failed: {r.stderr[-2000:]}")
    body = r.stdout.split("COLLECTIVE_HLO_START")[1].split(
        "COLLECTIVE_HLO_END")[0]
    return _collective_bytes(body)


def main():
    table = {"cpu_baseline_row_iters_per_sec": round(
        CPU_BASELINE_ROW_ITERS, 1),
        "gpu_target_multiple_X": GPU_TARGET_MULTIPLE,
        "gpu_target_row_iters_per_sec": round(
            GPU_TARGET_MULTIPLE * CPU_BASELINE_ROW_ITERS, 1),
        "gpu_target_source": ("docs/GPU-Tutorial.rst:162 'over three times "
                              "speedup' (half-M60 vs its own 6-vCPU box) "
                              "taken vs the FULL 28-core baseline — the "
                              "aggressive reading; the docs' only other "
                              "bound is 'budget GPU ... faster than a "
                              "28-core Haswell' (GPU-Performance.rst:172), "
                              "i.e. >=1x")}
    # end-to-end first: a fresh device gives the representative number
    it_1m = iter_time(1_000_000)
    table["iter_s_1m"] = round(it_1m, 4)
    table["row_iters_per_sec_1m"] = round(1_000_000 / it_1m, 1)
    y_legs = [1_000_000 / it_1m]
    if os.environ.get("FULL", "0") == "1":
        it_full = iter_time(10_500_000)
        table["iter_s_10m5"] = round(it_full, 4)
        table["row_iters_per_sec_10m5"] = round(10_500_000 / it_full, 1)
        y_legs.append(10_500_000 / it_full)
    y = min(y_legs) / CPU_BASELINE_ROW_ITERS
    table["single_chip_multiple_Y"] = round(y, 3)
    table["needed_8chip_scaling_Z"] = round(GPU_TARGET_MULTIPLE / y, 2)

    peak = measured_peak()
    table.update(peak)
    print("peak:", peak, flush=True)
    table["wave_kernel"] = wave_times(peak["peak_int8_tmacs"])
    table["wave_kernel_note"] = (
        "ns_per_row is the 1M->4M dispatch-wall slope; dispatch-latency "
        "variance (~+-1 ms per point) puts ~+-0.3 ns/row error bars on "
        "it, so small-wave utilizations carry wide bars (values near or "
        "above 1.0 mean 'at the MXU roofline within measurement error', "
        "not >100%).  peak_*_tmacs itself under-reads ~5-10%: each "
        "chained step pays a clip+cast epilogue on the 67 MB product.")
    print("waves:", table["wave_kernel"], flush=True)

    B = collective_bytes_per_tree()
    table["allreduce_bytes_per_tree_B"] = B
    table["assumed_ici_effective_GBps"] = ICI_EFFECTIVE_GBPS
    t_ici = B / (ICI_EFFECTIVE_GBPS * 1e9)
    table["ici_s_per_tree"] = round(t_ici, 6)
    # per-chip compute for a 10.5M-row tree split 8 ways ~= the measured
    # 1M-row iteration (1.31M rows/chip; wave cost is ~linear in rows
    # above 1M — fixed overheads are the sub-linear part, so this
    # UNDERSTATES 8-chip efficiency slightly -> conservative)
    t_comp = it_1m * (10.5e6 / 8) / 1_000_000
    table["per_chip_compute_s_per_tree_C"] = round(t_comp, 4)
    eff = t_comp / (t_comp + t_ici)          # worst case: zero overlap
    table["projected_8chip_scaling_no_overlap"] = round(8 * eff, 2)
    proj = (10.5e6 / (t_comp + t_ici)) / CPU_BASELINE_ROW_ITERS
    table["projected_8chip_multiple"] = round(proj, 2)
    table["beats_gpu_target"] = bool(proj >= GPU_TARGET_MULTIPLE)
    table["recorded_on"] = "TPU v5e (bench device), round 5"

    os.makedirs(os.path.dirname(ARTIFACT), exist_ok=True)
    with open(ARTIFACT, "w") as f:
        json.dump(table, f, indent=1)
    print(json.dumps(table, indent=1))
    print("wrote", ARTIFACT)


if __name__ == "__main__":
    main()
