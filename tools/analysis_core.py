"""Shared plumbing for the repo's static analyzers (tpulint, spmdcheck,
memcheck, detcheck, concheck, numcheck): file loading, one process-wide
AST cache, inline suppression parsing, the content-keyed baseline, and
the fixture EXPECT matcher.

History: this started life as ``tools/tpulint/core.py`` (PR 3) and was
imported wholesale by spmdcheck (PR 4).  With memcheck as the third
consumer the plumbing moved here (``tools/tpulint/core.py`` remains a
re-export shim so existing imports keep working); detcheck (PR 12) is
the fourth rider, concheck (PR 18) the fifth, and numcheck (PR 19) the
sixth.

Design invariants every analyzer relies on:

* **One parse per file per process** — ASTs are cached on
  ``(path, mtime, size)``; running tpulint + spmdcheck + memcheck +
  detcheck in one process (``python -m tools.check``, or the four
  tier-1 gate tests in one pytest session) parses each package file
  exactly once.
* **Suppression syntax** is shared across analyzers, keyed by tag::

      x = np.asarray(v)  # tpulint: disable=TPL003 -- host-only IO path
      y = jax.lax.psum(y, ax)  # spmdcheck: disable=SPM001 -- masked
      _SINK.append(a)  # memcheck: disable=MEM005 -- bounded by tests
      s *= 1 + j * random.random()  # detcheck: disable=DET001 -- jitter

  A disable comment applies to its own line, or — when the line is
  comment-only — to the next source line.  A disable WITHOUT a
  justification (the ``-- reason`` tail) is reported by tpulint as
  TPL000: every silenced hazard carries its why in-line.
* **Baselines** pin pre-existing findings so gates fail only on NEW
  ones.  Keys are ``file::rule::<stripped source line>`` — line-content
  keyed, not line-number keyed, so unrelated edits above a pinned
  finding don't break the pin — with a count per key.  All three
  committed baselines are EMPTY and tests assert they stay that way.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

# one regex serves every analyzer: each tool's tag suppresses its own
# rule ids (rule-id sets are disjoint, so cross-tag suppression is
# harmless and occasionally handy when one line trips two analyzers)
_SUPPRESS_RE = re.compile(
    r"#\s*(?:tpulint|spmdcheck|memcheck|detcheck|concheck|numcheck):"
    r"\s*disable="
    r"([A-Za-z0-9_,\s]+?)(?:\s*--\s*(.*))?\s*$")

# fixture EXPECT markers (tests): `# EXPECT: TPL001` on the flagged
# line, `# EXPECT-NEXT: MEM004` on the line above it
_EXPECT_RE = re.compile(
    r"#\s*EXPECT(-NEXT)?:\s*([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)")


@dataclass(frozen=True)
class Finding:
    """One hazard: ``file`` is root-relative posix, ``line`` 1-based."""
    file: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} {self.message}"


@dataclass
class FileInfo:
    """A parsed source file plus its per-line suppression map."""
    path: str                       # absolute
    rel: str                        # root-relative, posix separators
    source: str
    lines: List[str]
    tree: ast.Module
    # line -> set of suppressed rule ids ("*" = all)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    # lines whose disable comment carries no justification
    unjustified: List[int] = field(default_factory=list)

    @property
    def basename(self) -> str:
        return os.path.basename(self.rel)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def imports_jax(self) -> bool:
        for node in self.tree.body:
            if isinstance(node, ast.Import):
                if any(a.name.split(".")[0] == "jax" for a in node.names):
                    return True
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "jax":
                    return True
        return False


def _parse_suppressions(fi: FileInfo) -> None:
    for i, raw in enumerate(fi.lines, 1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = (m.group(2) or "").strip()
        # comment-only disable line covers the next SOURCE line (a
        # justification may wrap onto further comment lines)
        target = i
        if raw.strip().startswith("#"):
            target = i + 1
            while (target <= len(fi.lines)
                   and (not fi.lines[target - 1].strip()
                        or fi.lines[target - 1].strip().startswith("#"))):
                target += 1
        fi.suppressions.setdefault(target, set()).update(rules or {"*"})
        if not reason:
            fi.unjustified.append(i)


# -- AST cache ------------------------------------------------------------
_AST_CACHE: Dict[str, Tuple[Tuple[float, int], FileInfo]] = {}


def load_file(path: str, root: str) -> Optional[FileInfo]:
    """Parse ``path`` (cached on mtime+size); None on syntax errors —
    a file the interpreter itself rejects is not an analyzer's job."""
    path = os.path.abspath(path)
    try:
        st = os.stat(path)
        stamp = (st.st_mtime, st.st_size)
    except OSError:
        return None
    cached = _AST_CACHE.get(path)
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    if cached is not None and cached[0] == stamp:
        fi = cached[1]
        if fi.rel != rel:           # same file analyzed under another root
            fi = FileInfo(path, rel, fi.source, fi.lines, fi.tree,
                          fi.suppressions, fi.unjustified)
        return fi
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError):
        return None
    fi = FileInfo(path=path, rel=rel, source=source,
                  lines=source.splitlines(), tree=tree)
    _parse_suppressions(fi)
    _AST_CACHE[path] = (stamp, fi)
    return fi


def discover_files(paths: Sequence[str], root: str) -> List[FileInfo]:
    """Expand files/directories into parsed FileInfos (sorted, deduped)."""
    seen: Dict[str, None] = {}
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        seen[os.path.join(dirpath, name)] = None
        elif p.endswith(".py"):
            seen[os.path.abspath(p)] = None
    out = []
    for path in sorted(seen):
        fi = load_file(path, root)
        if fi is not None:
            out.append(fi)
    return out


def suppressed(fi: FileInfo, finding: Finding) -> bool:
    rules = fi.suppressions.get(finding.line)
    return bool(rules) and ("*" in rules or finding.rule in rules)


# -- baseline -------------------------------------------------------------
def finding_key(f: Finding, fi: Optional[FileInfo]) -> str:
    text = fi.line_text(f.line) if fi is not None else ""
    return f"{f.file}::{f.rule}::{text}"


def count_keys(findings: Sequence[Finding],
               by_rel: Dict[str, FileInfo]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        k = finding_key(f, by_rel.get(f.file))
        counts[k] = counts.get(k, 0) + 1
    return counts


def load_baseline(path: str) -> Dict[str, int]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    entries = data.get("entries", {}) if isinstance(data, dict) else {}
    return {str(k): int(v) for k, v in entries.items()}


def write_baseline(path: str, findings: Sequence[Finding],
                   by_rel: Dict[str, FileInfo],
                   tool: str = "tools.tpulint") -> None:
    entries = count_keys(findings, by_rel)
    data = {"version": 1,
            "comment": f"pinned pre-existing findings; refresh with "
                       f"`python -m {tool} --update-baseline`",
            "entries": {k: entries[k] for k in sorted(entries)}}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)


def new_findings(findings: Sequence[Finding],
                 by_rel: Dict[str, FileInfo],
                 baseline: Dict[str, int]) -> List[Finding]:
    """Findings beyond the baselined count for their key (oldest-first
    occurrences of a key are considered the pinned ones)."""
    budget = dict(baseline)
    out = []
    for f in findings:
        k = finding_key(f, by_rel.get(f.file))
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out


# -- fixture EXPECT matcher (shared by the three gate test files) ---------
def expect_markers(path: str) -> Set[Tuple[int, str]]:
    """{(lineno, rule)} findings a fixture file declares it expects."""
    out: Set[Tuple[int, str]] = set()
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            m = _EXPECT_RE.search(line)
            if not m:
                continue
            target = lineno + 1 if m.group(1) else lineno
            for rule in m.group(2).split(","):
                out.add((target, rule.strip()))
    return out


def assert_fixtures_match(fixtures_dir: str, findings: Sequence[Finding]
                          ) -> int:
    """Assert the analyzer reported EXACTLY the (line, rule) pairs each
    fixture under ``fixtures_dir`` declares; returns the fixture count
    checked (callers assert a minimum so an empty dir can't pass)."""
    got: Dict[str, Set[Tuple[int, str]]] = {}
    for f in findings:
        got.setdefault(os.path.basename(f.file), set()).add(
            (f.line, f.rule))
    checked = 0
    for name in sorted(os.listdir(fixtures_dir)):
        if not name.endswith(".py"):
            continue
        expected = expect_markers(os.path.join(fixtures_dir, name))
        actual = got.get(name, set())
        assert actual == expected, (
            f"{name}: expected {sorted(expected)}, got {sorted(actual)}")
        checked += 1
    return checked
