#!/usr/bin/env python
"""Parameterized device-time capture CLI (obs/profiler.py harness).

Replaces the two throwaway scripts this repo accreted
(``trace_tree_build.py``: hardcoded 1M x 28f x 63-bin jitted
build_tree; ``trace_bench_block.py``: hardcoded 1M-row train_block
with max_bin as a bare argv) with ONE tool over the first-class
capture layer::

    python tools/profile_capture.py --leg tree  --rows 1000000 \
        --leaves 255 --max-bin 63 --features 28 --out /tmp/jtrace
    python tools/profile_capture.py --leg block --max-bin 255
    python tools/profile_capture.py --leg train --iters 16 --windows 4

Legs:

* ``tree``  — the raw jitted ``build_tree`` program (no boosting loop):
  warm once, then capture ``--reps`` dispatches.  The phase spans
  (``tree.route/.hist/.split_find/.update``) only exist on the
  unfused ``LGBM_TPU_TIMETAG=phases`` path; on the fused path the
  whole build is one program and the report's per-program table is
  the signal.
* ``block`` — a real ``Booster`` driving ``train_block`` (the fused
  production path): warm, then capture one ``--iters`` block window.
* ``train`` — the full ``lgb.train`` loop under the same windowed
  ``LGBM_TPU_PROFILE`` capture a bench run uses (warmup window, then
  ``--windows`` captured windows of ``LGBM_TPU_PROFILE_ITERS`` each).

Every leg ends by printing the parsed attribution report
(``tools/perf_report.py`` rendering: per-span device table, host gap,
top programs, roofline columns) — the capture dir keeps the raw trace
for xprof/perfetto.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _synthetic(n, f, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] * 2 + X[:, 1] - X[:, 2]
         + rng.normal(size=n) > 0).astype(np.float32)
    return X, y


def leg_tree(args):
    """Capture --reps dispatches of the raw jitted tree build."""
    import jax
    import jax.numpy as jnp
    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs
    from lightgbm_tpu.io.device import to_device
    from lightgbm_tpu.learner.serial import GrowthParams, build_tree
    from lightgbm_tpu.obs import profiler
    from lightgbm_tpu.ops.pallas_histogram import transpose_bins
    from lightgbm_tpu.ops.split import SplitParams

    X, y = _synthetic(args.rows, args.features)
    ds = lgb.Dataset(X, label=y, params={"max_bin": args.max_bin})
    ds.construct()
    dd = to_device(ds._constructed)
    del X
    params = GrowthParams(num_leaves=args.leaves,
                          split=SplitParams(min_data_in_leaf=20))
    rng = np.random.RandomState(0)
    grad = jnp.asarray(rng.normal(size=args.rows).astype(np.float32))
    hess = jnp.asarray(
        rng.uniform(0.1, 0.3, size=args.rows).astype(np.float32))
    bins_t = jax.jit(transpose_bins)(dd.bins)
    bt = jax.jit(lambda g, h: build_tree(dd, g, h, params, bins_t=bins_t))
    r = bt(grad, hess)
    jax.block_until_ready(r.leaf_value)             # warm: compile
    profiler.record_program_cost("tree.build", bt, (grad, hess),
                                 module_hint="jit_")
    with profiler.capture(
            args.out,
            sync=lambda: jax.block_until_ready(r.leaf_value)) as cap:
        for i in range(args.reps):
            with obs.span("gbdt.iteration", it=i), \
                    profiler.step("tree.build", i):
                r = bt(grad, hess)
        jax.block_until_ready(r.leaf_value)
    return cap.report


def leg_block(args):
    """Capture one train_block window on the fused production path."""
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.basic import Booster
    from lightgbm_tpu.obs import profiler

    X, y = _synthetic(args.rows, args.features)
    ds = lgb.Dataset(X, label=y, params={"max_bin": args.max_bin})
    ds.construct()
    del X
    params = {"objective": "binary", "num_leaves": args.leaves,
              "max_bin": args.max_bin, "learning_rate": 0.1,
              "min_data_in_leaf": 20, "verbose": -1}
    bst = Booster(params=params, train_set=ds)
    bst.update()
    g = bst._gbdt
    g.train_block(args.iters)                       # warm: compile
    jax.block_until_ready(g.scores)
    with profiler.capture(
            args.out,
            sync=lambda: jax.block_until_ready(g.scores)) as cap:
        g.train_block(args.iters)
        jax.block_until_ready(g.scores)
    return cap.report


def leg_train(args):
    """The full lgb.train loop under windowed LGBM_TPU_PROFILE capture
    — exactly what a profiled bench leg records."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs

    os.environ["LGBM_TPU_PROFILE"] = args.out
    os.environ.setdefault("LGBM_TPU_PROFILE_WINDOWS", str(args.windows))
    X, y = _synthetic(args.rows, args.features)
    ds = lgb.Dataset(X, label=y, params={"max_bin": args.max_bin})
    params = {"objective": "binary", "num_leaves": args.leaves,
              "max_bin": args.max_bin, "learning_rate": 0.1,
              "min_data_in_leaf": 20, "verbose": -1}
    lgb.train(params, ds, num_boost_round=args.iters)
    return obs.summary().get("device_attribution")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--leg", choices=("tree", "block", "train"),
                    default="block")
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--max-bin", type=int, default=63)
    ap.add_argument("--leaves", type=int, default=255)
    ap.add_argument("--iters", type=int, default=4,
                    help="block/train: boosting iterations")
    ap.add_argument("--reps", type=int, default=3,
                    help="tree: captured build dispatches")
    ap.add_argument("--windows", type=int, default=2,
                    help="train: captured windows after warmup")
    ap.add_argument("--out", default="",
                    help="capture dir (default /tmp/lgbm_profile_<leg>)")
    args = ap.parse_args(argv)
    if not args.out:
        args.out = f"/tmp/lgbm_profile_{args.leg}"
    os.makedirs(args.out, exist_ok=True)
    t0 = time.time()
    report = {"tree": leg_tree, "block": leg_block,
              "train": leg_train}[args.leg](args)
    print(f"\ncapture leg={args.leg} rows={args.rows} "
          f"features={args.features} max_bin={args.max_bin} "
          f"leaves={args.leaves} took {time.time() - t0:.1f}s "
          f"-> {args.out}")
    if report is None:
        print("no attribution report produced (capture failed to start?)")
        return 1
    from tools.perf_report import render
    render(report)
    return 1 if report.get("error") else 0


if __name__ == "__main__":
    sys.exit(main())
