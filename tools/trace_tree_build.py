import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import os, time, glob, gzip, json, collections
import numpy as np, jax, jax.numpy as jnp

n = 1_000_000; leaves = 255; max_bin = 63; f = 28
rng = np.random.RandomState(0)
X = rng.normal(size=(n, f)).astype(np.float32)
y = (X[:, 0]*2 + X[:, 1] - X[:, 2] + rng.normal(size=n) > 0).astype(np.float32)
import lightgbm_tpu as lgb
ds = lgb.Dataset(X, label=y, params={"max_bin": max_bin}); ds.construct()
from lightgbm_tpu.io.device import to_device
dd = to_device(ds._constructed); del X
from lightgbm_tpu.learner.serial import GrowthParams, build_tree
from lightgbm_tpu.ops.pallas_histogram import transpose_bins
from lightgbm_tpu.ops.split import SplitParams
params = GrowthParams(num_leaves=leaves, split=SplitParams(min_data_in_leaf=20))
grad = jnp.asarray(rng.normal(size=n).astype(np.float32))
hess = jnp.asarray(rng.uniform(0.1, 0.3, size=n).astype(np.float32))
bins_t = jax.jit(transpose_bins)(dd.bins)
bt = jax.jit(lambda g, h: build_tree(dd, g, h, params, bins_t=bins_t))
r = bt(grad, hess); jax.block_until_ready(r.leaf_value)

os.makedirs("/tmp/jtrace", exist_ok=True)
with jax.profiler.trace("/tmp/jtrace", create_perfetto_trace=True):
    for _ in range(3):
        r = bt(grad, hess)
    jax.block_until_ready(r.leaf_value)
print("trace done")
