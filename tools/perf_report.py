#!/usr/bin/env python
"""Render a device-time attribution report from a profiler capture.

Usage::

    python tools/perf_report.py <capture-dir | trace.json.gz | summary.json>

Accepts, in order of preference:

* a capture directory written under ``LGBM_TPU_PROFILE=<dir>`` (or by
  ``tools/profile_capture.py``) — the newest
  ``plugins/profile/<ts>/*.trace.json.gz`` session is parsed;
* a chrome-trace ``*.trace.json(.gz)`` file directly;
* a telemetry summary JSON (``<trace>.summary.json`` or any file whose
  top-level object carries a ``device_attribution`` section) — renders
  the already-parsed section without re-reading the trace.

Prints the per-span device-time table (the share column is of total
attributed device time), the host-gap / collective accounting, the
top programs by device time, and — when the capture ran with the XLA
cost model (``LGBM_TPU_PROFILE`` implies it) — the per-program
roofline columns: FLOPs, bytes accessed, arithmetic intensity,
%-of-peak FLOPs/BW against the ``obs/chip_specs.py`` table, and the
compute / memory / host ``bound`` verdict.
"""
import json
import os
import sys


def _load_summary_section(path):
    """-> the device_attribution dict if ``path`` is a summary JSON
    carrying one, else None."""
    if not os.path.isfile(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            head = f.read(1)
            if head != "{":
                return None
            f.seek(0)
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(data, dict):
        if "device_attribution" in data:
            return data["device_attribution"]
        if "spans" in data and "device_time_s" in data:
            return data                 # a bare attribution dict
    return None


def render(report, out=None):
    """Pretty-print one attribution report (the dict the profiler
    attaches as the ``device_attribution`` summary section)."""
    out = out if out is not None else sys.stdout   # late-bound: capsys
    p = lambda *a: print(*a, file=out)  # noqa: E731
    if report.get("error"):
        p(f"attribution FAILED: {report['error']}  "
          f"(source: {report.get('source')})")
        return
    p(f"capture: {report.get('source')}")
    dev = report.get("device_time_s") or 0.0
    p(f"device time: {dev:.6f}s over {report.get('ops', 0)} ops "
      f"({report.get('annotations', 0)} annotations)  "
      f"coverage: {report.get('coverage')}")
    p(f"wall: {report.get('capture_wall_s')}s   device busy: "
      f"{report.get('device_busy_s')}s   host gap (in windows): "
      f"{report.get('host_gap_s')}s of {report.get('window_wall_s')}s")
    p(f"collectives: {report.get('collective_s')}s "
      f"(frac {report.get('collective_frac')})")
    spans = report.get("spans") or {}
    if spans:
        p(f"\n{'span':<28s} {'ops':>9s} {'device_s':>12s} {'share':>7s}")
        p("-" * 60)
        for name, rec in spans.items():
            share = 100.0 * rec["device_s"] / dev if dev else 0.0
            p(f"{name:<28s} {rec['ops']:>9d} {rec['device_s']:>12.6f} "
              f"{share:>6.1f}%")
    top = report.get("top_programs") or []
    if top:
        p("\ntop programs by device time:")
        for mod, s in top:
            p(f"  {mod:<40s} {s:>12.6f}s")
    cm = report.get("cost_model") or {}
    rows = cm.get("programs") or []
    if rows:
        peaks = cm.get("peaks", {})
        sent = " [SENTINEL peaks]" if peaks.get("sentinel") else ""
        p(f"\nroofline vs {cm.get('device_kind')}{sent} "
          f"({peaks.get('source', 'no peak table')}):")
        p(f"{'program':<22s} {'flops':>12s} {'bytes':>12s} {'AI':>7s} "
          f"{'%flops':>7s} {'%bw':>7s} {'bound':>8s}")
        p("-" * 80)
        for r in rows:
            ai = r.get("arith_intensity")
            p(f"{r['program']:<22s} "
              f"{(r.get('flops') or 0):>12.3e} "
              f"{(r.get('bytes_accessed') or 0):>12.3e} "
              f"{(f'{ai:.2f}' if ai is not None else '-'):>7s} "
              f"{(str(r.get('pct_peak_flops')) or '-'):>7s} "
              f"{(str(r.get('pct_peak_bw')) or '-'):>7s} "
              f"{(r.get('bound') or '-'):>8s}")


def main(argv):
    if not argv:
        print(__doc__)
        return 1
    path = argv[0]
    report = _load_summary_section(path)
    if report is None:
        # package-root import dance: let `python tools/perf_report.py`
        # work without an installed package
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from lightgbm_tpu.obs.profiler import finalize_report
        report = finalize_report(path)
    render(report)
    return 1 if report.get("error") else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
