"""Interleaving fuzzer for the package's thread seams.

``tools/concheck`` proves lock discipline statically and
``obs/lock_contract.py`` watches it at runtime; this tool makes the
schedules that break undisciplined code actually HAPPEN.  For each seed
it randomizes the interpreter's thread switch interval
(``sys.setswitchinterval``) — forcing preemption at bytecode boundaries
a quiet machine never exercises — and drives the four seams where this
codebase's threads genuinely contend:

* ``coord``    — elastic coordinator membership churn: clients join,
  leave, and get fault-evicted (``rendezvous.drop_rank``) from
  concurrent socket threads while the membership view is sampled.
  Invariants: the generation counter never moves backwards, every
  sampled rank map is contiguous ``0..W-1`` in sorted member-id order
  (the deterministic-rank law), and a fully-drained world ends at
  ``world == 0``.
* ``server``   — ``PredictionServer`` submit vs. close: submitters race
  a closer.  Invariants: every admitted future resolves (exactly-once
  delivery — a stranded future means a request fell between the
  ``_closed`` check and the drain), results are correct, and
  ``submitted == resolved + failed`` with the worker thread dead.
* ``watchdog`` — ``Watchdog`` arm/disarm churn vs. the monitor.
  Invariants: a span disarmed before its deadline never fires, an
  abandoned arm always fires, and ``stop()`` really stops the monitor.
* ``ledger``   — ``FleetLedger`` concurrent ``put_line``/``close``.
  Invariants: the file holds exactly the lines written, every line is
  whole and parseable, and writes racing ``close`` are dropped, not
  torn.

The runtime lock contract is armed for the run (the seams construct
their locks after import, so wrappers engage): any contract violation —
acquisition-order cycle, unguarded access, held-past-deadline — fails
the fuzz like a seam invariant would.

Usage::

    python -m tools.interleave [--seeds N] [--seams coord,server,...]

``--seeds`` defaults to ``LGBM_TPU_INTERLEAVE_SEEDS`` (else 3).  Exit
0 = every seed clean, 1 = an invariant or contract violation (printed
with its seed, seam, and detail), 2 = usage error.  The tier-1 gate
(``tests/test_lock_contract.py``) runs a toy shape; CI soaks raise the
seed count.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List

# arm the runtime contract before the library modules create their
# locks: enabled() is read at lock construction
os.environ.setdefault("LGBM_TPU_LOCK_CONTRACT", "1")

_SWITCH_INTERVALS = (1e-6, 5e-6, 2e-5, 1e-4, 1e-3)


def _join_all(threads: List[threading.Thread], what: str,
              viol: List[str], timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    for t in threads:
        t.join(max(deadline - time.monotonic(), 0.1))
        if t.is_alive():
            viol.append(f"{what}: thread {t.name} still alive after "
                        f"{timeout:.0f}s — a wedged schedule")


# ---------------------------------------------------------------------------
# seam: fleet ledger
# ---------------------------------------------------------------------------
def seam_ledger(rng: random.Random, tmp: str) -> List[str]:
    from lightgbm_tpu.obs import fleet
    viol: List[str] = []
    nthreads, per = 4, 20

    # phase 1: pure concurrent appends — every line lands, whole
    path = os.path.join(tmp, f"ledger-{rng.randrange(1 << 30)}.jsonl")
    led = fleet.FleetLedger(path)

    def writer(tid: int, seed: int) -> None:
        r = random.Random(seed)
        for i in range(per):
            led.put_line("fuzz", tid=tid, i=i)
            if r.random() < 0.2:
                time.sleep(0)

    ts = [threading.Thread(target=writer, args=(k, rng.randrange(1 << 30)),
                           name=f"ledger-w{k}") for k in range(nthreads)]
    for t in ts:
        t.start()
    _join_all(ts, "ledger", viol)
    led.close()
    seen = set()
    with open(path, "r", encoding="utf-8") as f:
        for ln, line in enumerate(f, 1):
            try:
                rec = json.loads(line)
            except ValueError:
                viol.append(f"ledger: torn/unparseable line {ln}: "
                            f"{line[:80]!r}")
                continue
            seen.add((rec.get("tid"), rec.get("i")))
    want = {(k, i) for k in range(nthreads) for i in range(per)}
    if seen != want:
        viol.append(f"ledger: {len(want - seen)} line(s) lost, "
                    f"{len(seen - want)} unexpected (of {len(want)})")

    # phase 2: writes racing close — dropped whole, never torn
    path2 = os.path.join(tmp, f"ledger2-{rng.randrange(1 << 30)}.jsonl")
    led2 = fleet.FleetLedger(path2)

    def racer(seed: int) -> None:
        r = random.Random(seed)
        for i in range(per):
            led2.put_line("race", i=i)
            if r.random() < 0.3:
                time.sleep(0)

    ts2 = [threading.Thread(target=racer, args=(rng.randrange(1 << 30),),
                            name=f"ledger-r{k}") for k in range(2)]
    for t in ts2:
        t.start()
    time.sleep(rng.uniform(0.0, 0.01))
    led2.close()
    _join_all(ts2, "ledger", viol)
    with open(path2, "r", encoding="utf-8") as f:
        for ln, line in enumerate(f, 1):
            try:
                json.loads(line)
            except ValueError:
                viol.append(f"ledger: line {ln} torn by a racing "
                            f"close: {line[:80]!r}")
    return viol


# ---------------------------------------------------------------------------
# seam: stall watchdog
# ---------------------------------------------------------------------------
def seam_watchdog(rng: random.Random, tmp: str) -> List[str]:
    from lightgbm_tpu.obs import health
    viol: List[str] = []
    old_forensic = os.environ.get("LGBM_TPU_FORENSIC")
    os.environ["LGBM_TPU_FORENSIC"] = os.path.join(tmp, "forensic.json")
    try:
        # phase 1: disarm always beats a generous deadline — no fire
        wd = health.Watchdog("fuzz", deadline_s=30.0)

        def churn(seed: int) -> None:
            r = random.Random(seed)
            for i in range(20):
                wd.arm(f"span-{i}")
                if r.random() < 0.5:
                    time.sleep(0)
                wd.disarm()

        ts = [threading.Thread(target=churn,
                               args=(rng.randrange(1 << 30),),
                               name=f"wd-churn{k}") for k in range(3)]
        for t in ts:
            t.start()
        _join_all(ts, "watchdog", viol)
        if wd.fired.is_set():
            viol.append("watchdog: fired although every span was "
                        "disarmed well inside its 30s deadline")
        wd.stop()
        if wd._thread.is_alive():
            viol.append("watchdog: monitor thread survived stop()")

        # phase 2: an abandoned arm must fire (and name its span)
        wd2 = health.Watchdog("fuzz2", deadline_s=0.05)
        wd2.arm("abandoned-span")
        if not wd2.fired.wait(10.0):
            viol.append("watchdog: abandoned armed span never fired "
                        "within 10s (deadline 0.05s)")
        wd2.stop()
        if wd2._thread.is_alive():
            viol.append("watchdog: monitor thread survived stop() "
                        "after a fire")
    finally:
        if old_forensic is None:
            os.environ.pop("LGBM_TPU_FORENSIC", None)
        else:
            os.environ["LGBM_TPU_FORENSIC"] = old_forensic
        health.reset()
    return viol


# ---------------------------------------------------------------------------
# seam: prediction server
# ---------------------------------------------------------------------------
class _StubModel:
    """Duck-types the two methods PredictionServer calls on a
    CompiledModel; scoring is a host-side row sum so results are
    checkable without a device."""

    def warm(self, buckets, binned=False):
        self.warmed = list(buckets)

    def predict(self, X, raw_score=False, binned=False, pad=False):
        import numpy as np
        return np.asarray(X, np.float32).sum(axis=1)


def seam_server(rng: random.Random, tmp: str) -> List[str]:
    import numpy as np

    from lightgbm_tpu.serve.server import PredictionServer
    viol: List[str] = []
    srv = PredictionServer(_StubModel(), max_batch=64, max_wait_ms=0.5,
                           warmup=True)
    results: List[tuple] = []          # (future, expected ndarray)
    res_lock = threading.Lock()

    def submitter(seed: int) -> None:
        r = random.Random(seed)
        for _ in range(25):
            rows = np.asarray(
                [[r.uniform(-1, 1) for _ in range(4)]
                 for _ in range(r.randrange(1, 4))], np.float32)
            try:
                fut = srv.submit(rows)
            except RuntimeError:
                return                  # closed under us: admission denied
            with res_lock:
                results.append((fut, rows.sum(axis=1)))
            if r.random() < 0.3:
                time.sleep(0)

    ts = [threading.Thread(target=submitter,
                           args=(rng.randrange(1 << 30),),
                           name=f"srv-sub{k}") for k in range(3)]
    for t in ts:
        t.start()
    time.sleep(rng.uniform(0.0, 0.02))
    srv.close(timeout=30.0)
    _join_all(ts, "server", viol)
    for fut, want in results:
        if not fut.done():
            # exactly-once delivery: an admitted request fell into the
            # submit-vs-drain crack and its future will never resolve
            viol.append("server: admitted request's future never "
                        "resolved (submit raced the close drain)")
            continue
        if fut.exception() is not None:
            viol.append(f"server: request failed: {fut.exception()!r}")
            continue
        got = np.atleast_1d(np.asarray(fut.result()))
        if got.shape != want.shape or not np.allclose(got, want,
                                                      atol=1e-5):
            viol.append(f"server: wrong result (cross-request mixup): "
                        f"got {got!r} want {want!r}")
    st = srv.stats()
    if st["submitted"] != st["resolved"] + st["failed"]:
        viol.append(f"server: accounting leak — submitted "
                    f"{st['submitted']} != resolved {st['resolved']} + "
                    f"failed {st['failed']}")
    if st["pending"] != 0:
        viol.append(f"server: {st['pending']} request(s) still pending "
                    f"after close()")
    if srv._thread.is_alive():
        viol.append("server: worker thread survived close()")
    return viol


# ---------------------------------------------------------------------------
# seam: elastic coordinator
# ---------------------------------------------------------------------------
def seam_coord(rng: random.Random, tmp: str) -> List[str]:
    from lightgbm_tpu.parallel import elastic
    from lightgbm_tpu.utils import faults
    viol: List[str] = []
    coord = elastic.ElasticCoordinator(
        heartbeat_timeout_s=1.0,
        ledger_path=os.path.join(tmp, f"coord-{rng.randrange(1 << 30)}"
                                      ".jsonl"))
    addr = coord.start()
    samples: List[Dict] = []
    stop_sampling = threading.Event()

    def sampler() -> None:
        while not stop_sampling.is_set():
            samples.append(coord.membership())
            time.sleep(0.005)

    def churn(tid: int, seed: int) -> None:
        r = random.Random(seed)
        for _ in range(2):
            c = elastic.ElasticClient(addr, member=f"fuzz-{tid}",
                                      deadline_s=10.0,
                                      heartbeat_interval_s=0.05)
            try:
                c.join_world()
                time.sleep(r.uniform(0.0, 0.05))
                c.leave()
            except (elastic.GenerationChanged, elastic.EvictedError,
                    elastic.RankLostError):
                pass                    # typed churn outcomes are legal
            finally:
                c.close()

    sm = threading.Thread(target=sampler, name="coord-sampler")
    sm.start()
    ts = [threading.Thread(target=churn,
                           args=(k, rng.randrange(1 << 30)),
                           name=f"coord-churn{k}") for k in range(3)]
    for t in ts:
        t.start()
    # mid-churn, evict the newest member as a lost rank
    time.sleep(rng.uniform(0.0, 0.05))
    faults.inject("rendezvous.drop_rank", times=1)
    _join_all(ts, "coord", viol)
    faults.clear("rendezvous.drop_rank")
    stop_sampling.set()
    sm.join(5.0)
    final = coord.membership()
    coord.stop()

    gen = -1
    for s in samples + [final]:
        if s["generation"] < gen:
            viol.append(f"coord: generation moved backwards "
                        f"({gen} -> {s['generation']})")
        gen = max(gen, s["generation"])
        members = s["members"]
        ranks = sorted(m["rank"] for m in members)
        if ranks != list(range(len(members))):
            viol.append(f"coord: rank map not contiguous 0..W-1: "
                        f"{ranks} at generation {s['generation']}")
        by_id = sorted(members, key=lambda m: m["member"])
        if [m["rank"] for m in by_id] != list(range(len(by_id))):
            viol.append(
                f"coord: ranks not in sorted member-id order at "
                f"generation {s['generation']}: "
                f"{[(m['member'], m['rank']) for m in by_id]} — the "
                f"deterministic rank law is broken")
    if final["world"] != 0:
        viol.append(f"coord: {final['world']} member(s) left behind "
                    f"after every client left")
    return viol


SEAMS: Dict[str, Callable[[random.Random, str], List[str]]] = {
    "ledger": seam_ledger,
    "watchdog": seam_watchdog,
    "server": seam_server,
    "coord": seam_coord,
}


def run_seeds(seeds: int, seams: List[str]) -> List[str]:
    """Run every seam under ``seeds`` randomized schedules; returns the
    violation list (empty = clean)."""
    from lightgbm_tpu.obs import lock_contract
    failures: List[str] = []
    old_interval = sys.getswitchinterval()
    try:
        for seed in range(seeds):
            rng = random.Random(seed)
            sys.setswitchinterval(rng.choice(_SWITCH_INTERVALS))
            lock_contract.reset()
            with tempfile.TemporaryDirectory(
                    prefix="lgbm-tpu-interleave-") as tmp:
                for name in seams:
                    sub = random.Random(rng.randrange(1 << 30))
                    for v in SEAMS[name](sub, tmp):
                        failures.append(f"seed {seed} seam {name}: {v}")
            for v in lock_contract.violations():
                failures.append(f"seed {seed} lock contract: "
                                f"{v.get('detail', v)}")
    finally:
        sys.setswitchinterval(old_interval)
        lock_contract.reset()
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.interleave",
        description="schedule fuzzer for the package's thread seams")
    ap.add_argument("--seeds", type=int, default=None,
                    help="schedules per seam (default "
                         "LGBM_TPU_INTERLEAVE_SEEDS, else 3)")
    ap.add_argument("--seams", default=",".join(SEAMS),
                    help=f"comma list from: {','.join(SEAMS)}")
    try:
        args = ap.parse_args(argv)
    except SystemExit as exc:
        return 2 if exc.code not in (0, None) else 0
    if args.seeds is None:
        raw = os.environ.get("LGBM_TPU_INTERLEAVE_SEEDS", "")
        try:
            args.seeds = int(raw) if raw else 3
        except ValueError:
            print(f"bad LGBM_TPU_INTERLEAVE_SEEDS: {raw!r}",
                  file=sys.stderr)
            return 2
    seams = [s.strip() for s in args.seams.split(",") if s.strip()]
    unknown = [s for s in seams if s not in SEAMS]
    if unknown or not seams or args.seeds < 1:
        print(f"unknown seam(s) {unknown} (have: {','.join(SEAMS)})"
              if unknown else "need >=1 seed and >=1 seam",
              file=sys.stderr)
        return 2
    t0 = time.perf_counter()
    failures = run_seeds(args.seeds, seams)
    dt = time.perf_counter() - t0
    if failures:
        for f in failures:
            print(f"INTERLEAVE {f}")
        print(f"interleave: {len(failures)} violation(s) across "
              f"{args.seeds} seed(s) ({dt:.1f}s)")
        return 1
    print(f"interleave: clean ({args.seeds} seed(s) x "
          f"{len(seams)} seam(s), {dt:.1f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
