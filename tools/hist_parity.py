"""500-iteration histogram-precision parity run: bf16 vs hilo vs scatter.

The reference validated its GPU single-precision histograms with
500-iteration accuracy tables across datasets
(`/root/reference/docs/GPU-Performance.rst:135-161`).  This runs the
same-depth check for OUR three histogram accumulation modes on the
bench-shaped workload and records the table to
``tests/data/hist_parity.json``, which ``tests/test_hist_parity.py``
asserts against the reference's own parity tolerance.

Run on TPU:  python tools/hist_parity.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

N_TRAIN = 1_000_000
N_TEST = 200_000
ITERS = 500
LEAVES = 255
MAX_BIN = 63


def make_data(seed, n):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, 28)).astype(np.float32)
    logit = X[:, 0] * 2 + X[:, 1] - X[:, 2] + 0.5 * X[:, 3] * X[:, 4]
    y = (logit + rng.normal(size=n) > 0).astype(np.float32)
    return X, y


def auc(label, score):
    order = np.argsort(score, kind="stable")
    ranks = np.empty(len(score))
    ranks[order] = np.arange(1, len(score) + 1)
    npos = label.sum()
    nneg = len(label) - npos
    return float((ranks[label > 0.5].sum() - npos * (npos + 1) / 2)
                 / (npos * nneg))


def run_mode(mode, Xtr, ytr, Xte, yte):
    os.environ["LGBM_TPU_HIST_MODE"] = mode if mode != "scatter" else "bf16"
    os.environ["LGBM_TPU_HIST_BACKEND"] = ("scatter" if mode == "scatter"
                                           else "")
    # fresh process-level caches matter less than fresh modules: the env
    # vars are read at tree-build time, but jit caches key on the closure,
    # so use a subprocess per mode when run standalone (see __main__)
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(Xtr, label=ytr, params={"max_bin": MAX_BIN})
    params = {"objective": "binary", "num_leaves": LEAVES,
              "max_bin": MAX_BIN, "learning_rate": 0.1,
              "min_data_in_leaf": 20, "verbose": -1,
              "num_iterations": ITERS}
    t0 = time.time()
    bst = lgb.train(params, ds)
    wall = time.time() - t0
    pred = bst.predict(Xte, raw_score=True)
    return {"mode": mode, "iters": ITERS,
            "test_auc": round(auc(yte, pred), 6),
            "train_wall_s": round(wall, 1)}


def main():
    if len(sys.argv) > 1:
        # child: one mode, print one JSON line
        mode = sys.argv[1]
        Xtr, ytr = make_data(0, N_TRAIN)
        Xte, yte = make_data(1, N_TEST)
        print("PARITY_RESULT " + json.dumps(run_mode(mode, Xtr, ytr,
                                                     Xte, yte)))
        return
    import subprocess
    results = []
    for mode in ("bf16", "hilo", "scatter"):
        out = subprocess.run([sys.executable, os.path.abspath(__file__),
                              mode], capture_output=True, text=True,
                             timeout=3600)
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("PARITY_RESULT ")]
        if not line:
            print(out.stdout[-2000:], out.stderr[-2000:])
            raise SystemExit(f"mode {mode} failed")
        results.append(json.loads(line[0][len("PARITY_RESULT "):]))
        print(results[-1])
    table = {
        "workload": {"n_train": N_TRAIN, "n_test": N_TEST, "iters": ITERS,
                     "num_leaves": LEAVES, "max_bin": MAX_BIN,
                     "objective": "binary",
                     "data": "synthetic HIGGS-shaped (tools/hist_parity.py)"},
        "reference_tolerance": {
            "source": "docs/GPU-Performance.rst:135-161",
            "note": ("largest CPU-vs-GPU AUC delta in the reference's own "
                     "500-iter parity tables is ~0.0008 (Expo 0.776217 vs "
                     "0.777059); we gate at 0.002"),
            "max_auc_delta": 0.002},
        "results": results,
        "recorded_on": "TPU v5e (bench device), round 3",
    }
    path = os.path.join(ROOT, "tests", "data", "hist_parity.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(table, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
