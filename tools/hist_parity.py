"""500-iteration histogram-precision parity run: bf16 vs hilo vs scatter.

The reference validated its GPU single-precision histograms with
500-iteration accuracy tables across datasets
(`/root/reference/docs/GPU-Performance.rst:135-161`).  This runs the
same-depth check for OUR three histogram accumulation modes and records
the table to ``tests/data/hist_parity.json``, which
``tests/test_hist_parity.py`` asserts against the reference's own parity
tolerance.

Two comparisons:
  * bf16 vs hi+lo (~f32 accumulation) at FULL bench size (1M rows),
  * all three — bf16, hilo, and the exact-f32 XLA scatter oracle — on
    the same reduced workload (250k rows; the scatter path is the slow
    exact fallback, and a full-size 500-iteration scatter run exceeds
    the device's dispatch watchdog even per-iteration).

Run on TPU:  python tools/hist_parity.py
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

N_XL = 10_500_000     # the full-scale bench shape (VERDICT r4 #8): the
                      # int8h default's parity evidence must reach the
                      # largest shape the bench actually runs
N_FULL = 1_000_000
N_SMALL = 250_000
N_TEST = 200_000
ITERS = 500
LEAVES = 255
MAX_BIN = 63
ARTIFACT = os.path.join(ROOT, "tests", "data", "hist_parity.json")


def make_data(seed, n):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, 28)).astype(np.float32)
    logit = X[:, 0] * 2 + X[:, 1] - X[:, 2] + 0.5 * X[:, 3] * X[:, 4]
    y = (logit + rng.normal(size=n) > 0).astype(np.float32)
    return X, y


def auc(label, score):
    from lightgbm_tpu.metric.metrics import binary_auc
    return binary_auc(label, score)


def run_child(mode, n_train):
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.basic import Booster
    Xtr, ytr = make_data(0, n_train)
    Xte, yte = make_data(1, N_TEST)
    ds = lgb.Dataset(Xtr, label=ytr, params={"max_bin": MAX_BIN})
    ds.construct()
    params = {"objective": "binary", "num_leaves": LEAVES,
              "max_bin": MAX_BIN, "learning_rate": 0.1,
              "min_data_in_leaf": 20, "verbose": -1,
              "num_iterations": ITERS}
    bst = Booster(params=params, train_set=ds)
    g = bst._gbdt
    # warmup: first window compiles the block program; timing it mixed
    # XLA compile into the wall column (VERDICT r3 weak #4: bf16 cannot
    # be the slowest mode).  The recorded wall is steady-state,
    # extrapolated to the full 500 iterations.
    warm = 32
    g.train_block(warm)
    jax.block_until_ready(g.scores)
    t0 = time.time()
    g.train_block(ITERS - warm)
    jax.block_until_ready(g.scores)
    wall = (time.time() - t0) / (ITERS - warm) * ITERS
    pred = bst.predict(Xte, raw_score=True)
    return {"mode": mode, "n_train": n_train, "iters": ITERS,
            "test_auc": round(auc(yte, pred), 6),
            "train_wall_s": round(wall, 1),
            "wall_note": "steady-state (post-compile), scaled to 500"}


def save(results):
    table = {
        "workload": {"n_xl": N_XL, "n_full": N_FULL, "n_small": N_SMALL,
                     "n_test": N_TEST, "iters": ITERS,
                     "num_leaves": LEAVES, "max_bin": MAX_BIN,
                     "objective": "binary",
                     "data": "synthetic HIGGS-shaped (tools/hist_parity.py)"},
        "reference_tolerance": {
            "source": "docs/GPU-Performance.rst:135-161",
            "note": ("largest CPU-vs-GPU AUC delta in the reference's own "
                     "500-iter parity tables is ~0.0008 (Expo 0.776217 vs "
                     "0.777059); we gate at 0.002"),
            "max_auc_delta": 0.002},
        "results": results,
        "recorded_on": "TPU v5e (bench device), round 4",
    }
    os.makedirs(os.path.dirname(ARTIFACT), exist_ok=True)
    with open(ARTIFACT, "w") as f:
        json.dump(table, f, indent=1)


def main():
    if len(sys.argv) > 2:
        mode, n_train = sys.argv[1], int(sys.argv[2])
        print("PARITY_RESULT " + json.dumps(run_child(mode, n_train)))
        return
    legs = [("int8h", N_XL), ("hilo", N_XL),
            ("bf16", N_FULL), ("hilo", N_FULL), ("ghilo", N_FULL),
            ("hhilo", N_FULL), ("int8h", N_FULL), ("int8", N_FULL),
            ("int8hh", N_FULL),
            ("bf16", N_SMALL), ("hilo", N_SMALL), ("ghilo", N_SMALL),
            ("hhilo", N_SMALL), ("int8h", N_SMALL), ("int8", N_SMALL),
            ("int8hh", N_SMALL), ("scatter", N_SMALL)]
    results = []
    if os.path.exists(ARTIFACT):
        with open(ARTIFACT) as f:
            results = json.load(f)["results"]
    done = {(r["mode"], r["n_train"]) for r in results}
    for mode, n_train in legs:
        if (mode, n_train) in done:
            continue
        env = dict(os.environ)
        env["LGBM_TPU_HIST_MODE"] = mode if mode != "scatter" else "bf16"
        if mode == "scatter":
            env["LGBM_TPU_HIST_BACKEND"] = "scatter"
            # 500 iterations of the slow exact path in one fused block
            # would trip the dispatch watchdog
            env["LGBM_TPU_NO_BLOCK"] = "1"
        else:
            env.pop("LGBM_TPU_HIST_BACKEND", None)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), mode, str(n_train)],
            capture_output=True, text=True, timeout=3600, env=env)
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("PARITY_RESULT ")]
        if not line:
            print(out.stdout[-2000:], out.stderr[-2000:])
            raise SystemExit(f"leg {mode}@{n_train} failed")
        results.append(json.loads(line[0][len("PARITY_RESULT "):]))
        print(results[-1], flush=True)
        save(results)          # incremental: a late crash keeps the rest
    print("wrote", ARTIFACT)


if __name__ == "__main__":
    main()
