import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import numpy as np, jax, jax.numpy as jnp, re, sys
n = 1_000_000; leaves = 255; max_bin = 63
rng = np.random.RandomState(0)
X = rng.normal(size=(n, 28)).astype(np.float32)
y = (X[:, 0]*2 + X[:, 1] - X[:, 2] + rng.normal(size=n) > 0).astype(np.float32)
import lightgbm_tpu as lgb
ds = lgb.Dataset(X, label=y, params={"max_bin": max_bin}); ds.construct()
del X
params = {"objective": "binary", "num_leaves": leaves, "max_bin": max_bin,
          "learning_rate": 0.1, "min_data_in_leaf": 20, "verbose": -1}
from lightgbm_tpu.basic import Booster
bst = Booster(params=params, train_set=ds)
g = bst._gbdt
fn = g._block_fn(4)
lowered = fn.lower(g.device_data, g._bins_t, tuple(g._valid_device),
                   g.scores, tuple(g._valid_scores), jnp.float32(0.1),
                   jnp.int32(0), jnp.int32(4))
comp = lowered.compile()
txt = comp.as_text()
with open("/tmp/block_hlo.txt", "w") as f:
    f.write(txt)
print("dumped", len(txt), "chars")
