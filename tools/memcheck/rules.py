"""memcheck rules MEM001-MEM005 — device-memory & donation hazards.

tpulint pins intra-rank host-sync/recompile hazards, spmdcheck pins
cross-rank schedule divergence; memcheck pins the hazard class PR 7
hit for real: device-memory lifetime.  The triggering incident —
zero-copy ``np.asarray`` host reads of a buffer a ``donate_argnums``
jit had consumed flakily SIGSEGV'd tier-1 eval on CPU — was only
caught by rerunning tests; these rules make that class (and its
siblings: missed donations, per-dispatch footprint blowups, unguarded
Pallas VMEM, live-buffer leaks) fail the gate instead.

| id     | hazard                                                       |
|--------|--------------------------------------------------------------|
| MEM001 | host materialization (np.asarray/np.array/.item()/           |
|        | device_get/memoryview/np.frombuffer) of a name that an       |
|        | UNGATED donate_argnums jit in the same module may have       |
|        | consumed — the PR 7 segfault class.  A donation site guarded |
|        | by a backend gate (an enclosing ``if`` referencing a         |
|        | ``*donat*`` predicate, e.g. ``_donation_enabled()``) is the  |
|        | sanctioned idiom and exempts its donated names               |
| MEM002 | a jit-bound callable with NO donation path threading the     |
|        | same array name in and out (``x = step(x)``): every dispatch |
|        | allocates a second live copy of persistent state instead of  |
|        | updating in place                                            |
| MEM003 | static per-dispatch footprint model: the closed-form live-   |
|        | bytes estimate (tools/memcheck/footprint.py) at each         |
|        | declared representative shape (tools/memcheck/shapes.json)   |
|        | exceeds that target's HBM budget                             |
| MEM004 | a ``pallas_call`` site whose module references no VMEM-model |
|        | predicate (``lightgbm_tpu/ops/vmem.py`` ``VMEM_GUARDS``, or  |
|        | any ``*vmem*`` name) and is not dispatched through a module  |
|        | that does — the ADVICE-r5 Mosaic-crash class                 |
| MEM005 | device arrays captured in module globals or appended to      |
|        | module-level containers (live-buffer leak: module lifetime   |
|        | pins device memory for the whole process)                    |

Name resolution is deliberately coarse (same contract as tpulint's
call-graph walk): a donated name taints every same-named read in the
module, and the baseline/suppressions absorb the rare over-taint.
Suppression syntax is shared (``# memcheck: disable=MEMxxx -- why``).
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis_core import FileInfo, Finding
from tools.tpulint.callgraph import _callee_name
from tools.tpulint.rules import JAX_ALIASES, NP_ALIASES, _root_name

RULE_TITLES = {
    "MEM001": "host read of a possibly-donated buffer",
    "MEM002": "persistent state threaded through jit without donation",
    "MEM003": "per-dispatch footprint exceeds the target HBM budget",
    "MEM004": "pallas_call without a VMEM-model guard",
    "MEM005": "device array pinned by a module global / container",
}

# fallback guard registry when lightgbm_tpu/ops/vmem.py is not under
# the analyzed root (fixture temp dirs); kept in sync by
# tests/test_memcheck.py::test_guard_registry_matches_ops_vmem
DEFAULT_VMEM_GUARDS = (
    "pallas_config_ok", "fused_config_ok", "compact_config_ok",
    "hist_cell_ok", "hist_fold_cell_ok", "split_lane_chunk_features",
    "split_scan_chunk_features",
)

_DONATION_GATE_RE = re.compile(r"donat", re.IGNORECASE)
_VMEM_NAME_RE = re.compile(r"vmem", re.IGNORECASE)

_MATERIALIZE_NP = {"asarray", "array", "frombuffer"}
_ARRAY_CTORS = {"zeros", "ones", "full", "empty", "arange", "asarray",
                "array", "linspace", "eye"}


@dataclass
class MemContext:
    root: str
    files: List[FileInfo]
    by_rel: Dict[str, FileInfo]
    vmem_guards: Tuple[str, ...]
    project_rules: bool = True


def _load_vmem_guards(root: str) -> Tuple[str, ...]:
    """Statically read ``VMEM_GUARDS`` from the analyzed tree's
    ops/vmem.py (no library import — tools stay jax-free)."""
    path = os.path.join(root, "lightgbm_tpu", "ops", "vmem.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError, ValueError):
        return DEFAULT_VMEM_GUARDS
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "VMEM_GUARDS"
                and isinstance(node.value, (ast.Tuple, ast.List))):
            names = [el.value for el in node.value.elts
                     if isinstance(el, ast.Constant)
                     and isinstance(el.value, str)]
            if names:
                return tuple(names)
    return DEFAULT_VMEM_GUARDS


def build_context(files: Sequence[FileInfo], root: str,
                  project_rules: bool = True) -> MemContext:
    return MemContext(root=root, files=list(files),
                      by_rel={fi.rel: fi for fi in files},
                      vmem_guards=_load_vmem_guards(root),
                      project_rules=project_rules)


# -- shared helpers -------------------------------------------------------
def _leaf_name(node: ast.AST) -> Optional[str]:
    """`x` -> x, `self.scores` -> scores, `a.b.c` -> c."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_jit_call(node: ast.Call) -> bool:
    return _callee_name(node.func) in ("jit", "pjit")


def _donate_kw(node: ast.Call) -> Optional[ast.keyword]:
    for kw in node.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            return kw
    return None


def _donate_indices(kw: ast.keyword) -> Optional[List[int]]:
    """Constant donate_argnums indices, or None when unresolvable."""
    v = kw.value
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        return [v.value]
    if isinstance(v, (ast.Tuple, ast.List)):
        out = []
        for el in v.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)):
                return None
            out.append(el.value)
        return out
    return None


class _GateWalker:
    """Tracks, per AST node, whether any enclosing If/IfExp/While test
    references a donation-gate name (``*donat*``): the sanctioned
    backend-gating idiom (``if _donation_enabled(): ...``)."""

    def __init__(self, tree: ast.AST):
        self.gated_lines: Set[int] = set()
        self._walk(tree, False)

    @staticmethod
    def _test_is_gate(test: ast.AST) -> bool:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Name) and _DONATION_GATE_RE.search(sub.id):
                return True
            if isinstance(sub, ast.Attribute) and _DONATION_GATE_RE.search(
                    sub.attr):
                return True
        return False

    def _walk(self, node: ast.AST, gated: bool) -> None:
        if gated and hasattr(node, "lineno"):
            self.gated_lines.add(node.lineno)
        if isinstance(node, (ast.If, ast.While)):
            self._walk(node.test, gated)
            branch = gated or self._test_is_gate(node.test)
            # an `elif` chain is a nested If in orelse: the recursion
            # re-dispatches here, so each arm gets its own test's gate
            for stmt in list(node.body) + list(node.orelse):
                self._walk(stmt, branch)
            return
        if isinstance(node, ast.IfExp):
            self._walk(node.test, gated)
            branch = gated or self._test_is_gate(node.test)
            self._walk(node.body, branch)
            self._walk(node.orelse, branch)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, gated)


@dataclass
class _DonationInfo:
    """Per-file donation facts MEM001/MEM002 share."""
    # callee leaf names bound to an UNGATED donating jit -> donated
    # positional indices (None = unresolvable, treat all args donated)
    ungated_donating: Dict[str, Optional[List[int]]] = field(
        default_factory=dict)
    # callee leaf names bound to ANY donating jit (gated or not)
    donating_names: Set[str] = field(default_factory=set)
    # callee leaf names bound to a PLAIN jit (no donation anywhere)
    plain_jit_names: Set[str] = field(default_factory=set)
    # names donated at call sites of ungated donating callables
    donated_value_names: Set[str] = field(default_factory=set)
    # lines of direct `jax.jit(f, donate_argnums=..)(x)` immediate calls
    # contribute donated names too


def _dict_donation_kwargs(fn_node: ast.AST, gates: _GateWalker) -> Dict[
        str, bool]:
    """kwarg-dict names that receive a ``donate_argnums`` store inside
    ``fn_node`` -> whether that store is donation-gated."""
    out: Dict[str, bool] = {}
    for node in ast.walk(fn_node):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if (isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name)
                and isinstance(t.slice, ast.Constant)
                and t.slice.value in ("donate_argnums", "donate_argnames")):
            name = t.value.id
            gated = node.lineno in gates.gated_lines
            out[name] = out.get(name, True) and gated
    return out


def _collect_donation(fi: FileInfo) -> _DonationInfo:
    info = _DonationInfo()
    gates = _GateWalker(fi.tree)
    # kwarg-dict donation stores, resolved per enclosing function
    dict_kwargs: Dict[str, bool] = {}
    for node in ast.walk(fi.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            dict_kwargs.update(_dict_donation_kwargs(node, gates))

    def classify_jit(call: ast.Call) -> Tuple[bool, Optional[List[int]],
                                              bool]:
        """-> (donating, indices, gated)."""
        kw = _donate_kw(call)
        if kw is not None:
            return True, _donate_indices(kw), (
                call.lineno in gates.gated_lines)
        for k in call.keywords:
            if k.arg is None and isinstance(k.value, ast.Name) \
                    and k.value.id in dict_kwargs:       # jax.jit(f, **kw)
                return True, None, dict_kwargs[k.value.id]
        return False, None, False

    for node in ast.walk(fi.tree):
        # name = jax.jit(f, ...) / self.attr = jax.jit(f, ...)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _is_jit_call(node.value):
            donating, idx, gated = classify_jit(node.value)
            for t in node.targets:
                leaf = _leaf_name(t)
                if leaf is None:
                    continue
                if donating:
                    info.donating_names.add(leaf)
                    if not gated:
                        info.ungated_donating[leaf] = idx
                else:
                    info.plain_jit_names.add(leaf)
        # immediate call: jax.jit(f, donate_argnums=(0,))(x, ...)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Call) \
                and _is_jit_call(node.func):
            donating, idx, gated = classify_jit(node.func)
            if donating and not gated:
                args = node.args
                for i in (idx if idx is not None else range(len(args))):
                    if i < len(args):
                        leaf = _leaf_name(args[i])
                        if leaf is not None:
                            info.donated_value_names.add(leaf)
        # @jax.jit / @partial(jax.jit, donate_argnums=...) decorations
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    target = dec
                    if (_callee_name(dec.func) == "partial" and dec.args
                            and _callee_name(dec.args[0]) in ("jit", "pjit")):
                        target = dec
                    elif not _is_jit_call(dec):
                        continue
                    donating, idx, gated = classify_jit(target)
                    if donating:
                        info.donating_names.add(node.name)
                        if not gated:
                            info.ungated_donating[node.name] = idx
                    else:
                        info.plain_jit_names.add(node.name)
                elif _callee_name(dec) in ("jit", "pjit"):
                    info.plain_jit_names.add(node.name)

    # a name with any donating binding is not "plain"
    info.plain_jit_names -= info.donating_names

    # call sites of ungated donating callables -> donated value names
    for node in ast.walk(fi.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _leaf_name(node.func)
        if callee not in info.ungated_donating:
            continue
        idx = info.ungated_donating[callee]
        args = node.args
        for i in (idx if idx is not None else range(len(args))):
            if i < len(args):
                leaf = _leaf_name(args[i])
                if leaf is not None:
                    info.donated_value_names.add(leaf)
    return info


_DONATION_CACHE: Dict[str, Tuple[str, _DonationInfo]] = {}


def _donation(fi: FileInfo) -> _DonationInfo:
    cached = _DONATION_CACHE.get(fi.path)
    if cached is not None and cached[0] == fi.source:
        return cached[1]
    info = _collect_donation(fi)
    _DONATION_CACHE[fi.path] = (fi.source, info)
    return info


# -- MEM001 ---------------------------------------------------------------
def rule_mem001(fi: FileInfo, ctx: MemContext) -> List[Finding]:
    info = _donation(fi)
    if not info.donated_value_names:
        return []
    out: List[Finding] = []

    def flag(node: ast.AST, what: str, name: str) -> None:
        out.append(Finding(
            fi.rel, node.lineno, "MEM001",
            f"{what} of `{name}`, which an ungated donate_argnums jit "
            f"in this module may have consumed: on CPU the host view "
            f"aliases the donated XLA buffer and reads race the next "
            f"dispatch (the PR 7 SIGSEGV class); gate the donation on "
            f"a backend predicate (see gbdt._donation_enabled) or read "
            f"a fresh, undonated result"))

    for node in ast.walk(fi.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        # np.asarray / np.array / np.frombuffer / memoryview / device_get
        if (isinstance(func, ast.Attribute)
                and func.attr in _MATERIALIZE_NP
                and _root_name(func) in NP_ALIASES and node.args):
            leaf = _leaf_name(node.args[0])
            if leaf in info.donated_value_names:
                flag(node, f"np.{func.attr}() host view", leaf)
        elif (isinstance(func, ast.Attribute) and func.attr == "device_get"
              and node.args):
            leaf = _leaf_name(node.args[0])
            if leaf in info.donated_value_names:
                flag(node, "jax.device_get()", leaf)
        elif (isinstance(func, ast.Name) and func.id == "memoryview"
              and node.args):
            leaf = _leaf_name(node.args[0])
            if leaf in info.donated_value_names:
                flag(node, "memoryview() buffer-protocol read", leaf)
        elif (isinstance(func, ast.Attribute) and func.attr == "item"
              and not node.args):
            leaf = _leaf_name(func.value)
            if leaf in info.donated_value_names:
                flag(node, ".item()", leaf)
    return out


# -- MEM002 ---------------------------------------------------------------
def rule_mem002(fi: FileInfo, ctx: MemContext) -> List[Finding]:
    info = _donation(fi)
    if not info.plain_jit_names:
        return []
    out: List[Finding] = []
    for node in ast.walk(fi.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        callee = _leaf_name(call.func)
        if callee not in info.plain_jit_names:
            continue
        arg_names = {_leaf_name(a) for a in call.args} - {None}
        for t in node.targets:
            targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                else [t]
            for tt in targets:
                leaf = _leaf_name(tt)
                if leaf is not None and leaf in arg_names:
                    out.append(Finding(
                        fi.rel, node.lineno, "MEM002",
                        f"`{leaf}` threads in and out of jit-bound "
                        f"`{callee}` with no donation path: every "
                        f"dispatch keeps TWO live copies of the state "
                        f"instead of updating in place; add "
                        f"donate_argnums behind a backend gate (the "
                        f"gbdt.py block-fn idiom) or justify why the "
                        f"old buffer must stay live"))
    return out


# -- MEM003 ---------------------------------------------------------------
def rule_mem003_project(ctx: MemContext) -> List[Finding]:
    """Project-level rule: evaluate the closed-form footprint model at
    every target declared in tools/memcheck/shapes.json (absent file =>
    rule inactive, e.g. fixture temp roots)."""
    from .footprint import load_targets, target_footprint
    shapes_rel = "tools/memcheck/shapes.json"
    path = os.path.join(ctx.root, shapes_rel)
    targets, err = load_targets(path)
    if err is not None:
        return [Finding(shapes_rel, 1, "MEM003",
                        f"shapes.json unreadable: {err}")]
    out: List[Finding] = []
    for t in targets:
        fp = target_footprint(t)
        if fp.total_bytes > t.budget_bytes:
            top = ", ".join(f"{k}={v / 1e6:.0f}MB" for k, v in sorted(
                fp.parts.items(), key=lambda kv: -kv[1])[:3])
            out.append(Finding(
                shapes_rel, 1, "MEM003",
                f"target `{t.name}`: estimated per-dispatch live bytes "
                f"{fp.total_bytes / 1e9:.2f} GB exceed the declared "
                f"budget {t.budget_bytes / 1e9:.2f} GB (largest: {top});"
                f" shrink the working set or justify a budget raise in "
                f"shapes.json"))
    return out


# -- MEM004 ---------------------------------------------------------------
def _module_guard_names(fi: FileInfo, guards: Sequence[str]) -> bool:
    guard_set = set(guards)
    for node in ast.walk(fi.tree):
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        elif isinstance(node, ast.alias):
            ident = node.name.rsplit(".", 1)[-1]
        if ident is None:
            continue
        if ident in guard_set or _VMEM_NAME_RE.search(ident):
            return True
    return False


def _imported_module_stems(fi: FileInfo) -> Set[str]:
    stems: Set[str] = set()
    for node in ast.walk(fi.tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            stems.add(node.module.rsplit(".", 1)[-1])
        elif isinstance(node, ast.Import):
            for a in node.names:
                stems.add(a.name.rsplit(".", 1)[-1])
    return stems


def rule_mem004(fi: FileInfo, ctx: MemContext) -> List[Finding]:
    if "pallas_call" not in fi.source:
        return []
    calls = [n for n in ast.walk(fi.tree)
             if isinstance(n, ast.Call)
             and _callee_name(n.func) == "pallas_call"]
    if not calls:
        return []
    if _module_guard_names(fi, ctx.vmem_guards):
        return []
    # dispatch-seam exemption: another analyzed module imports this one
    # AND references a guard (the serial.py `resolve_backend` pattern
    # guarding pallas_route's kernels)
    stem = os.path.splitext(fi.basename)[0]
    for other in ctx.files:
        if other.rel == fi.rel:
            continue
        if stem in _imported_module_stems(other) \
                and _module_guard_names(other, ctx.vmem_guards):
            return []
    return [Finding(
        fi.rel, c.lineno, "MEM004",
        "pallas_call with no VMEM-model guard on its dispatch path: an "
        "infeasible config surfaces as a Mosaic compile crash (or "
        "silent VMEM thrash) instead of a fallback; key the config "
        "gate on lightgbm_tpu/ops/vmem.py (VMEM_GUARDS) like "
        "pallas_config_ok/compact_config_ok do") for c in calls]


# -- MEM005 ---------------------------------------------------------------
def _module_container_names(fi: FileInfo) -> Set[str]:
    names: Set[str] = set()
    for node in fi.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        value = node.value
        is_container = isinstance(value, (ast.List, ast.Dict, ast.Set)) or (
            isinstance(value, ast.Call)
            and _callee_name(value.func) in ("list", "dict", "set",
                                             "deque", "defaultdict"))
        if not is_container:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


def _is_device_array_expr(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            func = sub.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _ARRAY_CTORS
                    and _root_name(func) in JAX_ALIASES):
                return True
    return False


def rule_mem005(fi: FileInfo, ctx: MemContext) -> List[Finding]:
    if not fi.imports_jax():
        return []
    out: List[Finding] = []
    # (a) module-scope device-array constant: lives for the process
    for node in fi.tree.body:
        value = getattr(node, "value", None)
        if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                and value is not None and _is_device_array_expr(value):
            out.append(Finding(
                fi.rel, node.lineno, "MEM005",
                "device array bound at module scope: the buffer pins "
                "device memory for the whole process (and embeds as a "
                "compile-payload constant when closed over); build it "
                "inside the function or pass it as an argument"))
    # (b) appends into module-level containers: unbounded live-buffer
    # growth (the leak class the runtime watermark contract catches)
    containers = _module_container_names(fi)
    if containers:
        for node in ast.walk(fi.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("append", "extend", "add")
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in containers
                    and node.args):
                continue
            arg = node.args[0]
            # literals (strings, numbers) can't pin device buffers
            if isinstance(arg, ast.Constant):
                continue
            out.append(Finding(
                fi.rel, node.lineno, "MEM005",
                f"append into module-level container "
                f"`{node.func.value.id}`: if the value holds device "
                f"arrays this is an unbounded live-buffer leak (the "
                f"class the LGBM_TPU_MEM_CONTRACT watermark gate "
                f"catches at runtime); bound or scope the container, "
                f"or justify why growth is bounded"))
    return out


FILE_RULES: List[Callable[[FileInfo, MemContext], List[Finding]]] = [
    rule_mem001, rule_mem002, rule_mem004, rule_mem005,
]
PROJECT_RULES = [rule_mem003_project]
