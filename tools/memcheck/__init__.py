"""memcheck — device-memory & donation-safety analyzer.

The third static gate (after tpulint and spmdcheck), aimed at the
resource that gates every remaining scaling item: device memory.
Rules MEM001-MEM005 (see ``rules.py``) run as a tier-1 gate via
``tests/test_memcheck.py`` / ``python -m tools.check`` and by hand::

    python -m tools.memcheck [--update-baseline] [--footprint] [paths...]

Shares the analyzer plumbing in ``tools/analysis_core.py`` (one AST
parse per file per process, ``# memcheck: disable=MEMxxx -- why``
suppressions, content-keyed baseline — committed EMPTY).  The RUNTIME
half is the HBM watermark contract
(``lightgbm_tpu/obs/mem_contract.py``, ``LGBM_TPU_MEM_CONTRACT=1``);
this package only analyzes source.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from tools.analysis_core import (FileInfo, Finding, count_keys,
                                 discover_files, load_baseline,
                                 new_findings, suppressed, write_baseline)

from .rules import FILE_RULES, PROJECT_RULES, RULE_TITLES, build_context

BASELINE_DEFAULT = os.path.join("tools", "memcheck", "baseline.json")

__all__ = [
    "run_memcheck", "Finding", "RULE_TITLES", "load_baseline",
    "write_baseline", "new_findings", "BASELINE_DEFAULT",
]


def run_memcheck(paths: Sequence[str] = ("lightgbm_tpu",),
                 root: Optional[str] = None,
                 project_rules: bool = True,
                 ) -> Tuple[List[Finding], Dict[str, FileInfo]]:
    """Analyze ``paths``; returns (findings sorted by location, FileInfo
    by relative path).  Inline suppressions applied; the baseline is NOT
    — callers diff via :func:`new_findings` (same contract as tpulint).
    ``project_rules=False`` skips MEM003 (the shapes.json footprint
    gate) for fixture runs."""
    root = os.path.abspath(root or os.getcwd())
    files = discover_files(paths, root)
    ctx = build_context(files, root, project_rules=project_rules)
    findings: List[Finding] = []
    for fi in files:
        for rule in FILE_RULES:
            for f in rule(fi, ctx):
                if not suppressed(fi, f):
                    findings.append(f)
    if project_rules:
        for rule in PROJECT_RULES:
            findings.extend(rule(ctx))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings, ctx.by_rel


def render_footprints(root: Optional[str] = None) -> List[str]:
    """Human-readable per-target footprint table (the ``--footprint``
    CLI dump): every shapes.json target with its estimated live bytes,
    budget, and headroom."""
    from .footprint import load_targets, target_footprint
    root = os.path.abspath(root or os.getcwd())
    targets, err = load_targets(
        os.path.join(root, "tools", "memcheck", "shapes.json"))
    if err is not None:
        return [f"shapes.json unreadable: {err}"]
    lines = []
    for t in targets:
        fp = target_footprint(t)
        lines.append(
            f"{t.name} ({t.kind}): {fp.total_bytes / 1e9:.3f} GB "
            f"estimated / {t.budget_bytes / 1e9:.2f} GB budget "
            f"({fp.total_bytes / t.budget_bytes:.1%})")
        for k, v in sorted(fp.parts.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {k}: {v / 1e6:.1f} MB")
    return lines
