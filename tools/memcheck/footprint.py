"""MEM003's closed-form per-dispatch footprint model.

The reference engine budgets memory as a first-class design axis (its
``.bin`` dataset cache and histogram-pool sizing — SURVEY.md, LightGBM
v2.1.0); this module is the TPU port's analog: for each REPRESENTATIVE
shape declared in ``tools/memcheck/shapes.json`` (the bench legs:
1M/10.5M HIGGS, the MSLR 255-bin ranking store, the serve buckets),
estimate the LIVE device bytes of one training/serving dispatch in
closed form and gate it against that target's HBM budget.

The model mirrors the allocations the code actually makes (pure int
arithmetic — no jax import, so the static gate stays cheap and can run
where jax can't):

* binned store: ``[n, F]`` uint8 + the ``[F_pad, n_pad]`` transposed
  kernel copy (``transpose_bins`` pads rows to the row tile, features
  to 8);
* score state: ``[n, K]`` f32 train scores (+ valid scores when the
  target declares valid rows) — ONE live set with donation, two in the
  undonated A/B (the model charges the donated steady state and adds
  one extra set as dispatch headroom);
* gradients/hessians: 2x ``[n, K]`` f32 (donated into the build, so
  one generation live at a time; headroom charged as above);
* bagging mask ``[n]`` bool + routed leaf ``[n]`` i32 + row values
  ``[n]`` f32;
* histogram state: ``leaves x F x bin_stride x 3`` f32 (grad/hess/
  count per (leaf, feature, bin)) plus one in-flight wave accumulator
  ``128-slot x F x bin_stride x C(=5)`` f32 (the wide kernel's padded
  output block);
* block-scan tree stack: ``block_cap x leaves`` x ~8 i32/f32 fields;
* serve targets: the packed forest ``[T, M]`` node tensors (~9 x i32/
  f32 fields at ``M = 2*leaves``) + one padded ``[bucket, F]`` f32
  input + binned uint8 copy + ``[bucket, K]`` scores.

Numbers are ESTIMATES with a declared slack factor — the gate exists
to catch order-of-magnitude regressions (a new per-row f32 temp at
10.5M rows, a forgotten second score set) before a TPU run OOMs, not
to account every byte.
"""
from __future__ import annotations

import importlib.util
import json
import os as _os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

LANE = 128

# the wide histogram kernel's active-slot cap: the cached split scan's
# worst per-wave width is 2 x this (both children of every slot)
WAVE_SLOT_CAP = 128


def _load_vmem_module():
    """Load ``lightgbm_tpu/ops/vmem.py`` by PATH (pure int math, no jax
    import) so the split-scan chunk model has ONE home — importing the
    package would pull in jax, which this jax-free gate must not."""
    p = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      "..", "..", "lightgbm_tpu", "ops", "vmem.py")
    try:
        spec = importlib.util.spec_from_file_location("_memcheck_vmem", p)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except (OSError, ImportError, AttributeError, ValueError,
            SyntaxError):
        return None         # fallback formulas below


_VMEM = _load_vmem_module()


def _split_scan_part(slots: int, F: int, B: int) -> int:
    """Live bytes of one feature-chunked split scan over ``[slots, F,
    B]`` — the ~10-grid ``[2, slots, Fc, B]`` f32 stack of the
    missing-direction variant (ISSUE 9), with ``Fc`` from the shared
    chunk model (`ops/vmem.py split_scan_chunk_features`)."""
    if _VMEM is not None:
        fc = _VMEM.split_scan_chunk_features(slots, F, B)
        return _VMEM.split_scan_bytes(slots, fc, B)
    # fallback mirror of the vmem model (10 live [2, slots, Fc, B] f32)
    budget = 512 << 20
    per_f = 10 * 2 * slots * B * 4
    fc = min(F, max(1, budget // max(1, per_f)))
    return 10 * 2 * slots * fc * B * 4


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def bin_stride(max_bin: int) -> int:
    return max(8, _next_pow2(max_bin))


@dataclass
class Target:
    name: str
    kind: str                    # "train" | "serve" | "stream"
    budget_bytes: int
    rows: int = 0
    features: int = 0
    max_bin: int = 255
    leaves: int = 255
    classes: int = 1
    valid_rows: int = 0
    block_cap: int = 32
    devices: int = 1             # data-parallel mesh size (fused block)
    trees: int = 0               # serve
    bucket_rows: int = 0         # serve
    stream_rows: int = 0         # stream: LGBM_TPU_STREAM_ROWS block
    pipeline: bool = True        # stream: prefetch pipeline armed
    slack: float = 1.25


@dataclass
class Footprint:
    parts: Dict[str, int] = field(default_factory=dict)

    @property
    def total_bytes(self) -> int:
        return int(sum(self.parts.values()))


def load_targets(path: str) -> Tuple[List[Target], Optional[str]]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return [], None             # no shapes declared: rule inactive
    except (OSError, ValueError) as exc:
        return [], f"{type(exc).__name__}: {exc}"
    out = []
    try:
        default_budget = int(data.get("default_budget_bytes", 14 << 30))
        for t in data.get("targets", []):
            out.append(Target(
                name=str(t["name"]), kind=str(t.get("kind", "train")),
                budget_bytes=int(t.get("budget_bytes", default_budget)),
                rows=int(t.get("rows", 0)),
                features=int(t.get("features", 0)),
                max_bin=int(t.get("max_bin", 255)),
                leaves=int(t.get("leaves", 255)),
                classes=int(t.get("classes", 1)),
                valid_rows=int(t.get("valid_rows", 0)),
                block_cap=int(t.get("block_cap", 32)),
                devices=max(1, int(t.get("devices", 1))),
                trees=int(t.get("trees", 0)),
                bucket_rows=int(t.get("bucket_rows", 0)),
                stream_rows=int(t.get("stream_rows", 0)),
                pipeline=bool(t.get("pipeline", True)),
                slack=float(t.get("slack", 1.25))))
    except (KeyError, TypeError, ValueError) as exc:
        return [], f"bad target spec: {type(exc).__name__}: {exc}"
    return out, None


def train_footprint(t: Target) -> Footprint:
    """Per-DEVICE live bytes of one training dispatch.  ``devices > 1``
    models the fused data-parallel mesh block program under the
    partition-rule registry (`parallel/partition.py`): row-sharded
    arrays (``data/bins`` and its transposed kernel copy, grad/hess,
    bag mask, routed leaves) charge each device 1/d of the row axis,
    while the registry's REPLICATED arrays (scores, valid state) and
    the psum'd full-width histogram state stay whole per device."""
    n, F, K = t.rows, t.features, max(1, t.classes)
    B = bin_stride(t.max_bin)
    # per-device row shard (rows pad to a device multiple before the
    # shard, so ceil covers the padded block)
    n_dev = -(-t.rows // t.devices)
    n_pad = _round_up(n_dev, 2048)
    F_pad = _round_up(F, 8)
    fp = Footprint()
    fp.parts["bins"] = n_dev * F                   # [n/d, F] uint8 shard
    fp.parts["bins_transposed"] = F_pad * n_pad    # [F_pad, n_pad/d] uint8
    # one live score generation (donated in-place update) + one
    # dispatch-headroom set for the result materializing before the
    # donor is released.  REPLICATED per the scores partition rule:
    # host eval reads the full [n, K] on every device
    fp.parts["scores"] = 2 * n * K * 4
    if t.valid_rows:
        fp.parts["valid_scores"] = 2 * t.valid_rows * K * 4
        fp.parts["valid_bins"] = t.valid_rows * F
    fp.parts["grad_hess"] = 2 * 2 * n_dev * K * 4
    fp.parts["bag_mask"] = n_dev
    fp.parts["row_leaf_values"] = n_dev * 4 + n_dev * 4
    # full sibling-subtract histogram state + one in-flight wave block
    fp.parts["hist_state"] = t.leaves * F * B * 3 * 4
    wave_cols = _round_up(5 * 128, LANE)     # C=5 cols x 128-slot cap
    fp.parts["wave_hist"] = F * B * wave_cols * 4
    # split-scan intermediates (ISSUE 9): the per-wave scan's ~10-grid
    # f32 stack, feature-chunked under the shared vmem model.  Charged
    # at the WORSE of the cached width (2 x the 128-slot wave cap) and
    # the cache-off full rescan over every leaf slot — the budget gate
    # must cover the escape-hatch A/B too
    scan_slots = max(min(2 * WAVE_SLOT_CAP, 2 * t.leaves), t.leaves)
    fp.parts["split_scan"] = _split_scan_part(scan_slots, F, B)
    fp.parts["tree_stack"] = t.block_cap * K * t.leaves * 8 * 4
    for k in fp.parts:
        fp.parts[k] = int(fp.parts[k] * t.slack)
    return fp


def serve_footprint(t: Target) -> Footprint:
    F, K = t.features, max(1, t.classes)
    M = 2 * t.leaves                              # padded node slots
    fp = Footprint()
    fp.parts["forest_pack"] = t.trees * M * 9 * 4  # [T, M] x ~9 fields
    fp.parts["input_batch"] = t.bucket_rows * F * 4
    fp.parts["binned_batch"] = t.bucket_rows * F
    fp.parts["scores"] = t.bucket_rows * K * 4
    fp.parts["walk_state"] = t.bucket_rows * 2 * 4  # per-row node cursor
    for k in fp.parts:
        fp.parts[k] = int(fp.parts[k] * t.slack)
    return fp


def stream_footprint(t: Target) -> Footprint:
    """Per-device live bytes of one streamed-training wave dispatch
    (ISSUE 14, ``boosting/streaming.py``): device memory is charged
    PER BLOCK — ``stream_rows`` rows in flight (one block live + one
    double-buffered upload), never the dataset — plus the resident
    per-leaf state (histograms, split cache, tree arrays), which is
    what the out-of-core memory contract means.  The ``rows`` field is
    documentation (the dataset scale the target represents); it never
    enters the device arithmetic, and the bench leg's runtime
    watermark (``stream_peak_hbm_bytes``) is the empirical half of the
    same claim.

    ISSUE 20: when the upload/compute ``pipeline`` is armed (the
    runtime default, ``LGBM_TPU_STREAM_PIPELINE``), block k+1's staged
    uploads land on device BEFORE block k's fold is awaited, so the
    steady state holds THREE block generations of bins/grad/hess (the
    computing block, the XLA double buffer, the staged next block)
    instead of two; and the kernel folds carry a RAW seeded
    accumulator (int32/f32 at the kernel's padded column layout) whose
    donated chain keeps one extra generation live at dispatch."""
    R, F, K = t.stream_rows, t.features, max(1, t.classes)
    B = bin_stride(t.max_bin)
    fp = Footprint()
    # blocks in flight: one computing + one XLA double buffer, +1 for
    # the pipeline's staged next block when armed
    depth = 3 if t.pipeline else 2
    fp.parts["block_bins"] = depth * R * F
    fp.parts["block_grad_hess"] = depth * 2 * R * 4
    fp.parts["block_leaf2"] = 2 * 2 * R * 4       # wave carry stays serial
    fp.parts["block_scores"] = 2 * R * K * 4      # score loop stays serial
    # resident per-leaf state: the wave accumulator (per shard), the
    # sibling-subtract histogram state, split-scan intermediates
    fp.parts["wave_acc"] = WAVE_SLOT_CAP * F * B * 3 * 4
    # the seeded kernel folds' raw carry (ISSUE 20): [F*B, cols] at the
    # wide kernel's padded column layout, two generations (donor +
    # result) live across a fold dispatch
    raw_cols = _round_up(5 * WAVE_SLOT_CAP, LANE)
    fp.parts["raw_fold_acc"] = 2 * F * B * raw_cols * 4
    fp.parts["hist_state"] = t.leaves * F * B * 3 * 4
    scan_slots = max(min(2 * WAVE_SLOT_CAP, 2 * t.leaves), t.leaves)
    fp.parts["split_scan"] = _split_scan_part(scan_slots, F, B)
    fp.parts["tree_arrays"] = K * t.leaves * 8 * 4
    for k in fp.parts:
        fp.parts[k] = int(fp.parts[k] * t.slack)
    return fp


def target_footprint(t: Target) -> Footprint:
    if t.kind == "serve":
        return serve_footprint(t)
    if t.kind == "stream":
        return stream_footprint(t)
    return train_footprint(t)
