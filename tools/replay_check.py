"""Train-twice replay harness: ``python -m tools.replay_check``.

The executable form of the reproducibility contract
(``obs/determinism.py``, ``LGBM_TPU_DETERMINISM=1``): every scenario
trains the SAME toy workload twice from identical seeds and asserts
the windowed model/score digest ledgers are IDENTICAL — serial,
bagged+feature-fraction, 2-shard data-parallel mesh, the keyed-RNG
DART, and GOSS.  A mismatch exits nonzero naming the FIRST diverging
window, which is the localization a real determinism bug needs (the
window bounds which iterations introduced it).

``--drift-proof`` additionally proves the wall trips: a DART run with
the ``det.rng_drift`` fault armed (``utils/faults.py`` — the keyed
drop derivation silently consumes the next iteration's draws) must
diverge from the clean ledger, and the harness must name the first
diverging window at or after the armed iteration.

Scenario ``mesh2`` needs two devices; on a single-device host the
harness re-execs itself in a child with a 2-device virtual CPU pool
(the bench ``--multichip-child`` pattern).

Usage::

    python -m tools.replay_check [--scenarios serial,bagged,mesh2,dart,goss]
                                 [--rows 600] [--rounds 8] [--drift-proof]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("LGBM_TPU_DETERMINISM", "1")

import numpy as np

SCENARIOS = ("serial", "bagged", "mesh2", "dart", "goss")

BASE_PARAMS = {"objective": "binary", "metric": "auc", "num_leaves": 7,
               "min_data_in_leaf": 5, "verbose": -1, "output_freq": 2,
               "learning_rate": 0.2}

SCENARIO_PARAMS: Dict[str, Dict] = {
    "serial": {},
    "bagged": {"bagging_fraction": 0.7, "bagging_freq": 1,
               "feature_fraction": 0.8},
    "mesh2": {"tree_learner": "data", "mesh_shape": [2]},
    "dart": {"boosting": "dart", "drop_rate": 0.5, "drop_seed": 4},
    "goss": {"boosting": "goss", "top_rate": 0.3, "other_rate": 0.2},
}


def _toy_data(rows: int, f: int = 6, seed: int = 7):
    """Synthetic binary data, pure in ``seed`` (counter-based Philox —
    the harness itself must satisfy its own contract)."""
    gen = np.random.Generator(np.random.Philox(key=[seed, 0]))
    X = gen.normal(size=(rows, f)).astype(np.float32)
    noise = np.random.Generator(np.random.Philox(key=[seed, 1])).normal(
        size=rows)
    y = (X[:, 0] + 0.5 * noise > 0).astype(np.float64)
    nv = max(64, rows // 4)
    Xv = np.random.Generator(np.random.Philox(key=[seed, 2])).normal(
        size=(nv, f)).astype(np.float32)
    vnoise = np.random.Generator(np.random.Philox(key=[seed, 3])).normal(
        size=nv)
    yv = (Xv[:, 0] + 0.5 * vnoise > 0).astype(np.float64)
    return X, y, Xv, yv


def run_once(scenario: str, rows: int, rounds: int,
             drift_at: Optional[int] = None
             ) -> Tuple[List, str, Dict]:
    """One training; -> (digest ledger [[it, digest], ...], final model
    digest, rng-ledger site counters)."""
    os.environ["LGBM_TPU_DETERMINISM"] = "1"
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import determinism
    from lightgbm_tpu.utils import faults
    X, y, Xv, yv = _toy_data(rows)
    params = {**BASE_PARAMS, **SCENARIO_PARAMS[scenario]}
    if drift_at is not None:
        faults.inject("det.rng_drift", times=1, skip=drift_at)
    try:
        tr = lgb.Dataset(X, label=y)
        bst = lgb.train(params, tr, num_boost_round=rounds,
                        valid_sets=[lgb.Dataset(Xv, label=yv,
                                                reference=tr)],
                        verbose_eval=False)
    finally:
        if drift_at is not None:
            faults.clear("det.rng_drift")
    sec = determinism.section()
    return sec["digests"], bst.digest(include_scores=False), sec["sites"]


def check_scenario(scenario: str, rows: int, rounds: int) -> Tuple[bool, str]:
    from lightgbm_tpu.obs import determinism
    a_digests, a_final, a_sites = run_once(scenario, rows, rounds)
    b_digests, b_final, b_sites = run_once(scenario, rows, rounds)
    div = determinism.first_divergence(a_digests, b_digests)
    if div is not None:
        it, da, db = div
        return False, (f"{scenario}: FAIL — first diverging window "
                       f"it={it} ({da[:12]} vs {db[:12]})")
    if a_final != b_final:
        return False, (f"{scenario}: FAIL — final model digest differs "
                       f"({a_final[:12]} vs {b_final[:12]})")
    if a_sites != b_sites:
        return False, (f"{scenario}: FAIL — RNG-ledger traffic differs "
                       f"({a_sites} vs {b_sites})")
    return True, (f"{scenario}: OK ({len(a_digests)} windows, "
                  f"model {a_final[:12]})")


def drift_proof(rows: int, rounds: int, drift_at: int = 3
                ) -> Tuple[bool, str]:
    """The wall must TRIP: an injected RNG drift in DART's keyed drop
    derivation has to diverge the ledger, first window named."""
    from lightgbm_tpu.obs import determinism
    clean, _, _ = run_once("dart", rows, rounds)
    drifted, _, _ = run_once("dart", rows, rounds, drift_at=drift_at)
    div = determinism.first_divergence(clean, drifted)
    if div is None:
        return False, ("drift-proof: FAIL — det.rng_drift armed at "
                       f"iteration {drift_at} but the digest ledgers "
                       "are identical: the contract is blind")
    it, da, db = div
    return True, (f"drift-proof: OK — injected drift at iteration "
                  f"{drift_at} localized to window it={it} "
                  f"({da[:12]} vs {db[:12]})")


def _mesh2_child(rows: int, rounds: int) -> Tuple[bool, str]:
    """Re-exec for the 2-shard scenario when this process has one
    device (XLA device count is fixed at jax init)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2"
                        ).strip()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.replay_check", "--scenarios",
         "mesh2", "--rows", str(rows), "--rounds", str(rounds)],
        env=env, capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("mesh2:")]
    tail = lines[-1] if lines else "mesh2: no output from child"
    return proc.returncode == 0, tail


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.replay_check",
        description="train-twice determinism replay harness (the "
                    "runtime half of detcheck)")
    parser.add_argument("--scenarios", default=",".join(SCENARIOS))
    parser.add_argument("--rows", type=int, default=600)
    parser.add_argument("--rounds", type=int, default=8)
    parser.add_argument("--drift-proof", action="store_true",
                        help="also prove det.rng_drift trips the wall")
    parser.add_argument("--json", action="store_true",
                        help="emit one machine-readable JSON line")
    args = parser.parse_args(argv)

    wanted = [s.strip() for s in args.scenarios.split(",") if s.strip()]
    bad = [s for s in wanted if s not in SCENARIOS]
    if bad:
        print(f"replay_check: unknown scenario(s) {bad}", file=sys.stderr)
        return 2

    import jax
    results: List[Tuple[str, bool, str]] = []
    for s in wanted:
        if s == "mesh2" and len(jax.devices()) < 2:
            ok, msg = _mesh2_child(args.rows, args.rounds)
        else:
            ok, msg = check_scenario(s, args.rows, args.rounds)
        results.append((s, ok, msg))
        print(msg)
    if args.drift_proof:
        ok, msg = drift_proof(args.rows, args.rounds)
        results.append(("drift-proof", ok, msg))
        print(msg)

    failed = [s for s, ok, _ in results if not ok]
    if args.json:
        print(json.dumps({"replay_check_ok": not failed,
                          "scenarios": {s: ok for s, ok, _ in results}}))
    if failed:
        print(f"replay_check: FAIL ({', '.join(failed)})")
        return 1
    print(f"replay_check: ok ({len(results)} scenario(s) digest-"
          f"identical twice)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
