#!/usr/bin/env python
"""Open-loop Poisson load harness for a live ``PredictionServer``.

The measurement instrument ROADMAP item 3c specifies: tail latency is
only a contract if it is measured under OFFERED load, not achieved
load.  A closed-loop generator (send, wait, send) self-throttles the
moment the server slows down — exactly when the tail matters — and
reports flattering percentiles (the classic coordinated-omission
trap).  This harness is open-loop: request arrival times are drawn
up-front from a Poisson process at the offered QPS (exponential
inter-arrival gaps, seeded), each request is submitted at its absolute
scheduled time whether or not earlier requests have returned, and a
request's latency is measured from its SCHEDULED arrival to its
future's completion — queueing delay, coalescing wait, padding, and
scoring all included, generator slip charged to the server side where
it belongs.

Per offered-QPS step the sweep records: achieved QPS (completions over
the step's wall), rows/s, p50/p99/p99.9/mean/max latency (ms),
failures, and how many submissions slipped past their schedule.

Usage (module or CLI)::

    from tools.load_harness import sweep
    rows = sweep(server, pool, qps_list=[1000, 5000], duration_s=5.0)

    python tools/load_harness.py --qps 500,2000,8000 --duration 2 \
        [--model model.txt] [--port 0]

Without ``--model`` a toy booster is trained in-process (mechanics /
CPU smoke); ``--port`` mounts the ops plane so ``/metrics`` can be
scraped while the sweep runs.  Output: one JSON line per step plus a
final ``{"serve_load_table": [...]}`` line (the bench ``serve_load``
leg consumes :func:`sweep` directly).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import numpy as np

# runnable as `python tools/load_harness.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_step(server, pool: np.ndarray, qps: float, duration_s: float,
             *, rows_per_request: int = 1, seed: int = 0,
             timeout_s: float = 120.0) -> Dict:
    """One open-loop step at ``qps`` offered for ``duration_s``."""
    rng = np.random.RandomState(seed)
    n_req = max(1, int(round(qps * duration_s)))
    # absolute Poisson schedule, drawn before the clock starts
    arrivals = np.cumsum(rng.exponential(1.0 / qps, size=n_req))
    done_at: Dict[int, float] = {}
    futs: List[Future] = []
    k = rows_per_request
    n_pool = pool.shape[0]
    t0 = time.perf_counter()
    late = 0
    for i in range(n_req):
        wait = arrivals[i] - (time.perf_counter() - t0)
        if wait > 0:
            time.sleep(wait)
        else:
            late += 1           # generator slipped; submit anyway (open loop)
        off = (i * 131) % max(1, n_pool - k)
        fu = server.submit(pool[off:off + k])

        def _cb(f, i=i):
            done_at[i] = time.perf_counter()

        fu.add_done_callback(_cb)
        futs.append(fu)
    failures = 0
    for fu in futs:
        try:
            fu.result(timeout=timeout_s)
        # a failed request still counts against the offered load; its
        # latency is excluded (there is no completion to measure)
        except Exception:       # noqa: BLE001 - recorded, not raised
            failures += 1
    t_end = time.perf_counter()
    lat_s = np.asarray([done_at[i] - t0 - arrivals[i]
                        for i in range(n_req) if i in done_at])
    ok = n_req - failures
    wall = max(t_end - t0, 1e-9)
    row = {
        "offered_qps": round(float(qps), 1),
        "achieved_qps": round(ok / wall, 1),
        "requests": n_req,
        "failures": failures,
        "late_submits": late,
        "rows_per_request": k,
        "rows_per_sec": round(ok * k / wall, 1),
        "duration_s": round(wall, 3),
    }
    if lat_s.size:
        row.update({
            "p50_ms": round(float(np.percentile(lat_s, 50)) * 1e3, 3),
            "p99_ms": round(float(np.percentile(lat_s, 99)) * 1e3, 3),
            "p999_ms": round(float(np.percentile(lat_s, 99.9)) * 1e3, 3),
            "mean_ms": round(float(lat_s.mean()) * 1e3, 3),
            "max_ms": round(float(lat_s.max()) * 1e3, 3),
        })
    return row


def sweep(server, pool: np.ndarray, qps_list: Sequence[float],
          duration_s: float, *, rows_per_request: int = 1, seed: int = 0,
          emit=None) -> List[Dict]:
    """Run :func:`run_step` at each offered QPS (low to high so an
    overloaded server's backlog never bleeds into a lighter step's
    tail), optionally emitting each row as it lands."""
    rows = []
    for i, qps in enumerate(sorted(qps_list)):
        row = run_step(server, pool, float(qps), duration_s,
                       rows_per_request=rows_per_request, seed=seed + i)
        rows.append(row)
        if emit is not None:
            emit(row)
    return rows


def _toy_server(features: int = 5, buckets=(64, 256)):
    """Train a toy booster in-process and wrap it in a server (the
    no-model CLI path and the CPU smoke test)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve import PredictionServer, compile_model
    rng = np.random.RandomState(3)
    X = rng.normal(size=(4_000, features)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 15})
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1}, ds, num_boost_round=4)
    cm = compile_model(bst)
    srv = PredictionServer(cm, max_batch=max(buckets), max_wait_ms=1.0,
                           buckets=buckets, min_bucket=min(buckets),
                           raw_score=True)
    return srv, X


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model", default=None,
                    help="model text file (default: toy in-process train)")
    ap.add_argument("--qps", default="200,1000",
                    help="comma-separated offered-QPS sweep")
    ap.add_argument("--duration", type=float, default=2.0,
                    help="seconds per sweep step")
    ap.add_argument("--rows-per-request", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--port", default=None,
                    help="mount the ops plane on this port "
                         "(sets LGBM_TPU_OPS_PORT; 0 = ephemeral)")
    args = ap.parse_args(argv)
    if args.port is not None:
        os.environ["LGBM_TPU_OPS_PORT"] = str(args.port)
    if args.model:
        import lightgbm_tpu as lgb
        from lightgbm_tpu.serve import PredictionServer, compile_model
        cm = compile_model(lgb.Booster(model_file=args.model))
        srv = PredictionServer(cm, raw_score=True)
        rng = np.random.RandomState(args.seed)
        pool = rng.normal(size=(8_192, cm.num_features)).astype(np.float32)
    else:
        srv, pool = _toy_server()
    qps_list = [float(q) for q in args.qps.split(",") if q.strip()]
    try:
        rows = sweep(srv, pool, qps_list, args.duration,
                     rows_per_request=args.rows_per_request,
                     seed=args.seed,
                     emit=lambda r: print(json.dumps(r), flush=True))
    finally:
        srv.close()
    print(json.dumps({"serve_load_table": rows}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
