"""``python -m tools.numcheck`` entry point."""
import sys

from .cli import main

sys.exit(main())
