"""Declarative reduction registry — the ground truth for NUM001/NUM005.

The byte-identity contract (PR 11/14/16) holds only because every
floating-point reduction whose result feeds persistent state is either

* a **canonical reducer** — an explicit, order-pinned reduction tree
  (``learner/serial.py``'s ``_pairwise_halve`` family) that XLA cannot
  legally reassociate, so serial / streamed / elastic partitionings
  reassemble bit-identical scalars from per-block partials; or
* a **partition-independent sum** — a reduction whose operand order can
  never vary with the partitioning (per-query pair grids, per-tree
  axes, single-nonzero selections), so raw ``jnp.sum`` is exact-enough
  by construction and stays sanctioned HERE, with its argument written
  down.

Everything else is a NUM001 finding: the exact bug class PR 14 had to
retrofit out when a raw ``jnp.sum`` over the root statistics silently
broke partition-invariance.

Each entry names its module (root-relative), the function whose BODY may
raw-reduce (for ``contexts``) or which IS the sanctioned reducer (for
``reducers``), and the one-line justification.  The NUM000 project rule
validates every entry resolves to a real function in a real module, so
the registry can never drift into fiction.
"""
from __future__ import annotations

# -- canonical reducers ----------------------------------------------------
# Functions that ARE the order-pinned reduction discipline.  Raw
# reductions inside their bodies are the implementation of the
# contract, not a violation of it.
REDUCERS = (
    {"name": "_pairwise_halve",
     "module": "lightgbm_tpu/learner/serial.py",
     "why": "explicit pairwise a+b halving tree: IEEE-defined adds XLA "
            "cannot reassociate, identical in every fusion context"},
    {"name": "root_chunk_sums",
     "module": "lightgbm_tpu/learner/serial.py",
     "why": "fixed STREAM_CHUNK grid anchored at row 0, zero-padded: "
            "per-block folds reassemble the identical [3, m] partials"},
    {"name": "reduce_chunk_sums",
     "module": "lightgbm_tpu/learner/serial.py",
     "why": "pads the chunk axis to a power of two and pairwise-halves: "
            "the tree depends only on m, never on the partitioning"},
    {"name": "root_stats",
     "module": "lightgbm_tpu/learner/serial.py",
     "why": "composition of the two canonical stages (the PR 14 "
            "retrofit that replaced the raw jnp.sum)"},
)

# -- partition-independent contexts ----------------------------------------
# Functions whose raw reductions are sanctioned because the operand
# order is a pure function of (data, config) — it cannot vary with how
# rows are partitioned across devices, blocks, or shards.
CONTEXTS = (
    {"function": "_select_miss_bin",
     "module": "lightgbm_tpu/ops/split.py",
     "why": "single-nonzero selection: is_miss_cell is one-hot over the "
            "bin axis, so the sum picks exactly one histogram cell — "
            "exact in any order"},
    {"function": "_fold_pair_grid",
     "module": "lightgbm_tpu/objective/objectives.py",
     "why": "lambdarank per-query [T, T] pair-grid folds: rows of one "
            "query are never split across partitions (ranking descopes "
            "row-blocked streaming), so the fold order is fixed by the "
            "in-query sort alone"},
    {"function": "_sum_tree_axis",
     "module": "lightgbm_tpu/models/tree.py",
     "why": "per-tree axis sum: trees are replicated model state and "
            "the tree axis is never partitioned, so the operand order "
            "is partition-independent"},
    {"function": "_select_row_leaf",
     "module": "lightgbm_tpu/learner/serial.py",
     "why": "single-nonzero selection: each row is in exactly one leaf, "
            "so the leaf-axis sum picks one value — exact in any order"},
    {"function": "_abs_grad_importance",
     "module": "lightgbm_tpu/boosting/variants.py",
     "why": "GOSS per-row class-axis sum: the class axis K is never "
            "partitioned (rows shard, classes replicate), and the "
            "importance only ranks rows — order is partition-"
            "independent"},
    {"function": "make_hist_fold_fn",
     "module": "lightgbm_tpu/learner/serial.py",
     "why": "accumulator-SEEDED streamed kernel folds (ISSUE 20): each "
            "block's kernel call seeds its output from the carry via "
            "input_output_aliases, replaying the monolithic kernel's "
            "adds in the monolithic order — exact int32 on quantized "
            "modes, identical per-tile f32 add sequence on the wide "
            "float modes (float compact degrades to wide); pinned "
            "streamed==resident per backend by tests/test_streaming.py"},
    {"function": "_fold_scales",
     "module": "lightgbm_tpu/boosting/streaming.py",
     "why": "per-(tree, shard) quantization scales as a chunked host "
            "absmax: f32 max/abs are exact and order-independent "
            "(idempotent commutative max, no rounding), so the chunked "
            "host reduction equals the device max(|x|) bitwise"},
)

# the explicit cross-device combine seam: psum/all-reduce of per-shard
# partials is elementwise in device order — the documented combine
# point, not a reassociation hazard (reordering happens ABOVE it, at
# shard granularity, which the shard protocol pins)
PSUM_FUNCS = frozenset({"psum", "all_reduce", "allreduce", "pmean"})

# -- persistent-state name flow (NUM001 taint) -----------------------------
# identifiers that mark an array as flowing from persistent training
# state: gradients, hessians, scores, histograms and their local
# aliases.  Matching is by exact id or substring, mirroring the other
# walls' coarse name-based resolution.
STATE_EXACT = frozenset({
    "g", "h", "G", "H", "gg", "hh", "gb", "hb", "signed", "per_tree",
})
STATE_SUBSTRINGS = (
    "grad", "hess", "score", "hist", "leaf_value",
)

# -- fenced state (NUM005) -------------------------------------------------
# score-state names whose mul+add updates must go through the PR 11/14
# fence discipline (optimization_barrier + pre-scaled .at[].add / the
# scale-then-gather shape) — a bare `scores = scores + lr * x` invites
# FMA contraction with partition-dependent last-ulp rounding.
FENCED_STATE = frozenset({
    "scores", "vscores", "valid_scores", "new_scores", "vs",
})
# fence helpers: functions registered as the blessed update shapes
FENCE_CONTEXTS = (
    {"function": "_make_block_fn",
     "module": "lightgbm_tpu/boosting/gbdt.py",
     "why": "the fenced block body: optimization_barrier + pre-scaled "
            ".at[].add updates (the PR 11 mesh discipline)"},
    {"function": "_score_update_fn",
     "module": "lightgbm_tpu/boosting/streaming.py",
     "why": "streamed per-block update compiled to the same fenced "
            "scale-then-gather shape as the in-memory body"},
)

# -- compensation idioms (NUM002) ------------------------------------------
# functions whose wide->narrow casts are COMPENSATED: the narrowing is
# paired with a residual (Neumaier / hi-lo split), so no precision is
# silently dropped.
COMPENSATED = (
    {"function": "split_hi_lo",
     "module": "lightgbm_tpu/ops/pallas_histogram.py",
     "why": "hi/lo split: x == hi + lo exactly; the narrow halves "
            "carry the full value between them"},
    {"function": "build_pack",
     "module": "lightgbm_tpu/serve/compiler.py",
     "why": "serve compiler hi/lo leaf pairs: lo = f32(v64 - f64(hi)) "
            "is the Neumaier residual of the narrowing cast"},
    {"function": "_f32_floor",
     "module": "lightgbm_tpu/serve/compiler.py",
     "why": "directed rounding, not accumulation: the narrowing is the "
            "documented threshold-floor contract (<= in f64 iff <= in "
            "f32 against the floored threshold)"},
)

# -- exact-identity comparison contexts (NUM003) ---------------------------
# operand-name substrings under which float == / != is sanctioned:
# digest/byte/text identity is the CONTRACT (byte-identical models),
# not a tolerance question.
EXACT_IDENTITY_SUBSTRINGS = (
    "digest", "hash", "sha", "bytes", "text", "fingerprint", "hexd",
)
# float-state operand names that make an == / != comparison a hazard
FLOAT_EQ_SUBSTRINGS = (
    "score", "metric", "loss", "gain", "grad", "hess", "auc",
    "leaf_value", "threshold",
)


def context_index():
    """(module, function) -> why, over every sanctioned-context table."""
    out = {}
    for table in (REDUCERS, CONTEXTS, FENCE_CONTEXTS, COMPENSATED):
        for d in table:
            out[(d["module"], d.get("function") or d["name"])] = d["why"]
    return out
