"""Declarative tolerance registry — every numeric comparison budget in
the test suite and the runtime numerics contract, as NAMED rows.

Before PR 19 ~70 ad-hoc ``atol=``/``rtol=`` magic constants sat in 20
test files with no owner; a tolerance is a CLAIM about how much two
computations may legally disagree, and an unowned claim decays into
"whatever makes the test pass".  Each row here carries the value, the
justification, and the owning parity contract.  The static half
(NUM004) requires every tolerance literal in a test to resolve to a
registered row — by name (``tol("f32_accum")``) for the migrated
files, by value for the long tail — so new magic constants cannot
land without a registry entry saying why.

The runtime half shares rows by NAME, the same way concheck's lock
registry shares names with ``obs/lock_contract.py``:
``obs/num_contract.py``'s ulp budgets and ``parallel/envelope.py``'s
margins must equal the rows declared here (``tests/test_numcheck.py``
pins the coherence).
"""
from __future__ import annotations

# id -> {value, unit, why, contract}
TOLERANCES = {
    # -- exact / byte-identity --------------------------------------------
    "exact": {
        "value": 0.0, "unit": "abs",
        "why": "bitwise agreement asserted through the allclose shape",
        "contract": "byte-identity (PR 11/14): partitionings agree "
                    "exactly, not approximately"},
    # -- f64 oracle comparisons -------------------------------------------
    "f64_solver": {
        "value": 1e-12, "unit": "rel",
        "why": "f64 computation vs an f64 closed-form oracle: only "
               "rounding of the final few ops",
        "contract": "ops oracles (tools/tpulint oracle docstrings)"},
    "f64_chain": {
        "value": 1e-9, "unit": "abs",
        "why": "longer f64 chains (leaf output, gain algebra) vs a "
               "NumPy f64 re-derivation",
        "contract": "ops oracles"},
    # -- f32 agreement ladders --------------------------------------------
    "f32_ulp_few": {
        "value": 1e-7, "unit": "abs",
        "why": "a few f32 ulps at unit scale: same math, different "
               "fusion context",
        "contract": "kernel parity (ops/)"},
    "f32_tight": {
        "value": 1e-6, "unit": "abs",
        "why": "~10 f32 ulps at unit scale: identical algorithm, "
               "reordered elementwise ops",
        "contract": "predict/save-load parity"},
    "f32_eps_few": {
        "value": 3e-6, "unit": "abs",
        "why": "tens of f32 ulps: short accumulation chains in a "
               "different order",
        "contract": "kernel parity (ops/)"},
    "f32_accum": {
        "value": 1e-5, "unit": "abs+rel",
        "why": "different-order f32 accumulation at unit scale (the "
               "reference's own cross-thread histogram envelope)",
        "contract": "histogram/predict parity"},
    "f32_accum_2x": {
        "value": 2e-5, "unit": "abs",
        "why": "two stacked f32 accumulation stages (device program "
               "vs host oracle, each with its own rounding)",
        "contract": "serve device-vs-host parity (serve/compiler.py)"},
    "f32_accum_5x": {
        "value": 5e-5, "unit": "abs",
        "why": "text round-trip (17 sig digits) + device re-"
               "accumulation stacked",
        "contract": "model text round-trip parity"},
    "f32_sum_wide": {
        "value": 1e-4, "unit": "abs+rel",
        "why": "wide f32 reductions (gains over many bins, SHAP "
               "contribution sums) in different orders",
        "contract": "split-finder / contribution parity"},
    "f32_rel_wide": {
        "value": 2e-4, "unit": "rel",
        "why": "relative form of the wide-reduction envelope for "
               "quantities far from unit scale",
        "contract": "split-finder parity"},
    "f32_wide_5x": {
        "value": 5e-4, "unit": "abs",
        "why": "bf16-assisted kernels (hilo histogram modes) vs f32 "
               "reference",
        "contract": "pallas kernel parity (ops/pallas_histogram.py)"},
    "metric_coarse": {
        "value": 1e-3, "unit": "abs+rel",
        "why": "end-to-end metric agreement after independently-"
               "rounded training paths",
        "contract": "engine/consistency parity"},
    "prob_coarse": {
        "value": 1e-2, "unit": "abs",
        "why": "probability-level agreement between structurally "
               "different but statistically equivalent models",
        "contract": "engine/consistency parity"},
    # -- the measured envelope (PR 4/8) -----------------------------------
    "envelope_value_noise": {
        "value": 0.0104, "unit": "abs",
        "why": "MEASURED serial-path leaf-value noise from f32 "
               "histogram accumulation order (parallel/envelope.py "
               "calibration run)",
        "contract": "model flip envelope (parallel/envelope.py "
                    "value_margin calibration)"},
    "envelope_rel": {
        "value": 0.05, "unit": "rel",
        "why": "near-tie margin: a flipped split pair only counts as "
               "divergence when its gain gap clears 5% of the larger "
               "gain",
        "contract": "model flip envelope (parallel/envelope.py "
                    "rel_margin; PR 4/8)"},
    "envelope_abs": {
        "value": 0.5, "unit": "abs",
        "why": "absolute gain floor for the near-zero-gain noise "
               "regime of the flip envelope",
        "contract": "model flip envelope (parallel/envelope.py "
                    "abs_margin; PR 4/8)"},
    # -- ulp budgets (shared with the runtime contract) --------------------
    "serve_ulp": {
        "value": 1, "unit": "ulp",
        "why": "serve scores within 1 f32 ulp of the f64 sequential "
               "tree-accumulation oracle (hi/lo compensated adds)",
        "contract": "serve parity (serve/compiler.py, PR 13)"},
    "score_root_ulp": {
        "value": 8, "unit": "ulp",
        "why": "per-window canonical f32 score root-sum vs the f64 "
               "host oracle: the pairwise tree loses < log2(chunks) "
               "ulps; 8 bounds every tier-1 workload with margin while "
               "a reassociated (partition-dependent) reduction drifts "
               "orders of magnitude past it",
        "contract": "runtime ulp contract (obs/num_contract.py, "
                    "LGBM_TPU_NUM_CONTRACT=1)"},
}


def tol(name):
    """The registered tolerance value for ``name`` (tests call this
    instead of writing magic constants; NUM004 enforces it)."""
    return TOLERANCES[name]["value"]


def registered_values():
    """Every registered numeric value, for NUM004's by-value resolution
    of the unmigrated long tail (plus 0/exact in int form)."""
    vals = {float(d["value"]) for d in TOLERANCES.values()}
    vals.add(0.0)
    return vals
