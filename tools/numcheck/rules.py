"""numcheck rules: NUM000-NUM005 — floating-point reproducibility
discipline, statically.

The sixth wall.  Five analyzers guard host-syncs, schedules, HBM, RNG,
and locks; every one of them silently assumes the floating-point layer
underneath is partition-invariant — and PR 14 proved by counterexample
that a single raw ``jnp.sum`` on persistent state can break the
byte-identity contract without tripping any of them.  numcheck pins
that lesson as rules, with the same philosophy as the other walls:
coarse name-based resolution, a declarative registry as ground truth
(``reduction_registry.py`` + ``tolerance_registry.py``), and the rare
over-taint handled by an inline ``# numcheck: disable=NUMxxx -- why``,
never by a baseline entry.

Rules:

* **NUM000** — registry inconsistency: a sanctioned reducer/context
  naming a module or function that does not exist, an entry with no
  justification, or a malformed tolerance row.
* **NUM001** — reassociation-unsafe reduction: ``jnp.sum``/``mean``/
  ``dot`` (or the ``.sum()`` method form) over arrays whose names flow
  from persistent training state (grad/hess/scores/hist families) in a
  jax-importing module, outside a registered canonical reducer or
  sanctioned partition-independent context.  XLA's ``reduce`` order is
  implementation-defined and varies with the surrounding program — the
  exact PR 14 bug class.
* **NUM002** — uncompensated wide-to-narrow accumulation: a cast to
  f32 whose operand derives from f64 (names/dtypes marked 64) without
  a registered compensation idiom (Neumaier residual / hi-lo split).
* **NUM003** — float ``==``/``!=`` on score/metric/gain-flavored
  operands outside the registered exact-identity contexts (digest /
  byte / model-text comparisons are the contract and stay sanctioned).
* **NUM004** — unregistered tolerance: an ``atol=``/``rtol=``/
  envelope-margin numeric literal that resolves to no row of
  ``tolerance_registry.py`` — by name for migrated call sites
  (``tol("f32_accum")``), by value for the long tail.
* **NUM005** — unfenced mul+add update of registered fenced score
  state (``scores = scores + lr * x``) outside the PR 11/14 fence
  helpers: the shape XLA contracts into FMAs with fusion-context-
  dependent last-ulp rounding (the lesson the optimization-barrier +
  scale-then-gather discipline exists for).
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analysis_core import FileInfo, Finding

from . import reduction_registry as reg
from . import tolerance_registry as tolreg

RULE_TITLES = {
    "NUM000": "numeric registry inconsistency",
    "NUM001": "reassociation-unsafe reduction on persistent state",
    "NUM002": "uncompensated wide-to-narrow accumulation",
    "NUM003": "float equality outside exact-identity contexts",
    "NUM004": "unregistered tolerance literal",
    "NUM005": "unfenced mul+add update of fenced score state",
}

_REDUCE_ATTRS = {"sum", "mean", "dot"}
_TOL_KEYWORDS = {"atol", "rtol", "rel_margin", "abs_margin",
                 "value_margin"}
_REDUCE_MODULES = {"jnp", "np", "numpy", "jax"}


# ---------------------------------------------------------------------------
# context
# ---------------------------------------------------------------------------
@dataclass
class NumContext:
    root: str
    files: List[FileInfo]
    by_rel: Dict[str, FileInfo]
    project_rules: bool
    # (module rel, function name) -> justification, from the registry
    sanctioned: Dict[Tuple[str, str], str] = field(default_factory=dict)
    # rel -> set of function names defined anywhere in the file
    defined_funcs: Dict[str, Set[str]] = field(default_factory=dict)


def build_context(files: Sequence[FileInfo], root: str,
                  project_rules: bool = True) -> NumContext:
    ctx = NumContext(root=root, files=list(files),
                     by_rel={fi.rel: fi for fi in files},
                     project_rules=project_rules,
                     sanctioned=reg.context_index())
    for fi in files:
        names: Set[str] = set()
        for node in ast.walk(fi.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)
        ctx.defined_funcs[fi.rel] = names
    return ctx


def _is_test_file(fi: FileInfo) -> bool:
    return (fi.basename.startswith("test_")
            or fi.rel.startswith("tests/") or "/tests/" in fi.rel)


def _module_matches(rel: str, module: str) -> bool:
    return rel == module or rel.endswith("/" + module)


def _sanctioned_here(ctx: NumContext, fi: FileInfo,
                     func_stack: Sequence[str]) -> Optional[str]:
    """The justification if ANY enclosing function is registered for
    this module, else None."""
    for (module, func), why in ctx.sanctioned.items():
        if func in func_stack and _module_matches(fi.rel, module):
            return why
    return None


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------
def _names_in(node: ast.AST) -> Set[str]:
    """Every Name id and Attribute attr in a subtree — the coarse
    name-flow the walls share."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


_INT_VALUED = {"len", "argmax", "argmin", "argsort", "searchsorted"}


def _names_for_float_flavor(node: ast.AST) -> Set[str]:
    """Names in a comparison operand, EXCLUDING subtrees under
    int-valued calls (``len(scores)`` compares a length, not a
    float)."""
    out: Set[str] = set()
    skip: Set[int] = set()
    for n in ast.walk(node):
        if id(n) in skip:
            skip.update(id(c) for c in ast.iter_child_nodes(n))
            continue
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id in _INT_VALUED:
            skip.update(id(c) for c in ast.iter_child_nodes(n))
            continue
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _INT_VALUED:
            skip.update(id(c) for c in ast.iter_child_nodes(n))
            continue
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _state_taint(names: Iterable[str]) -> Optional[str]:
    for name in sorted(names):
        if name in reg.STATE_EXACT:
            return name
        low = name.lower()
        if any(sub in low for sub in reg.STATE_SUBSTRINGS):
            return name
    return None


def _has_marker(names: Iterable[str], substrings: Sequence[str]) -> bool:
    return any(sub in name.lower()
               for name in names for sub in substrings)


def _mentions_f64(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and "64" in n.attr:
            return True
        if isinstance(n, ast.Name) and "64" in n.id:
            return True
        if isinstance(n, ast.Constant) and n.value in ("float64", "f64"):
            return True
    return False


def _contains_mul_add(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Add):
            for side in (n.left, n.right):
                for m in ast.walk(side):
                    if (isinstance(m, ast.BinOp)
                            and isinstance(m.op, ast.Mult)):
                        return True
    return False


class _Walker(ast.NodeVisitor):
    """One pass per file carrying the enclosing-function stack."""

    def __init__(self, fi: FileInfo, ctx: NumContext):
        self.fi = fi
        self.ctx = ctx
        self.stack: List[str] = []
        self.findings: List[Finding] = []
        self.is_test = _is_test_file(fi)
        self.traced = fi.imports_jax()

    # -- plumbing ---------------------------------------------------------
    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(Finding(self.fi.rel, node.lineno, rule,
                                     message))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- NUM001 / NUM002 / NUM004 -----------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        self._check_tolerance(node)
        if not self.is_test:
            self._check_reduction(node)
            self._check_narrowing(node)
        self.generic_visit(node)

    def _check_reduction(self, node: ast.Call) -> None:
        if not self.traced:
            return
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _REDUCE_ATTRS):
            return
        if func.attr in reg.PSUM_FUNCS:
            return
        if isinstance(func.value, ast.Name) \
                and func.value.id in _REDUCE_MODULES:
            # module form: jnp.sum(x, ...) — taint from the arguments
            operands: List[ast.AST] = list(node.args) \
                + [kw.value for kw in node.keywords if kw.value is not None]
        else:
            # method form: x.sum() — taint from the receiver + args
            operands = [func.value] + list(node.args)
        names: Set[str] = set()
        for op in operands:
            names |= _names_in(op)
        taint = _state_taint(names)
        if taint is None:
            return
        if _sanctioned_here(self.ctx, self.fi, self.stack) is not None:
            return
        self._emit(node, "NUM001",
                   f"reassociation-unsafe reduction '{func.attr}' over "
                   f"persistent f32 state ('{taint}') in traced code: "
                   f"XLA reduce order is implementation-defined and "
                   f"partition-dependent — use a canonical reducer "
                   f"(learner/serial.py root_stats family) or register "
                   f"the site in tools/numcheck/reduction_registry.py")

    def _check_narrowing(self, node: ast.Call) -> None:
        func = node.func
        inner: Optional[ast.AST] = None
        if isinstance(func, ast.Attribute) and func.attr == "astype" \
                and node.args:
            arg_names = _names_in(node.args[0])
            if "float32" in arg_names or any(
                    isinstance(a, ast.Constant)
                    and a.value in ("float32", "f32")
                    for a in node.args):
                inner = func.value
        elif isinstance(func, ast.Attribute) and func.attr == "float32" \
                and len(node.args) == 1:
            inner = node.args[0]
        if inner is None or not _mentions_f64(inner):
            return
        if _sanctioned_here(self.ctx, self.fi, self.stack) is not None:
            return
        self._emit(node, "NUM002",
                   "uncompensated wide-to-narrow accumulation: an f64-"
                   "derived value is cast to f32 with no registered "
                   "compensation idiom (Neumaier residual / hi-lo "
                   "split) — precision silently dropped; see "
                   "tools/numcheck/reduction_registry.py COMPENSATED")

    def _check_tolerance(self, node: ast.Call) -> None:
        for kw in node.keywords:
            if kw.arg not in _TOL_KEYWORDS:
                continue
            v = kw.value
            if not (isinstance(v, ast.Constant)
                    and isinstance(v.value, (int, float))
                    and not isinstance(v.value, bool)):
                continue
            if float(v.value) in tolreg.registered_values():
                continue
            self._emit(v, "NUM004",
                       f"unregistered tolerance literal "
                       f"{kw.arg}={v.value!r}: every comparison budget "
                       f"must resolve to a named row of tools/numcheck/"
                       f"tolerance_registry.py — use tol('<id>') or "
                       f"add a justified entry")

    # -- NUM003 -----------------------------------------------------------
    def visit_Compare(self, node: ast.Compare) -> None:
        if not self.is_test and any(
                isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            operands = [node.left] + list(node.comparators)
            names: Set[str] = set()
            for op in operands:
                names |= _names_for_float_flavor(op)
            if _has_marker(names, reg.FLOAT_EQ_SUBSTRINGS) \
                    and not _has_marker(names,
                                        reg.EXACT_IDENTITY_SUBSTRINGS):
                self._emit(node, "NUM003",
                           "float == / != on score/metric-flavored "
                           "state: exact float comparison is only "
                           "sound for digest/byte identity — compare "
                           "digests, or use a registered tolerance "
                           "(tools/numcheck/tolerance_registry.py)")
        self.generic_visit(node)

    # -- NUM005 -----------------------------------------------------------
    def _fenced_target(self, target: ast.AST) -> Optional[str]:
        if isinstance(target, ast.Name) and target.id in reg.FENCED_STATE:
            return target.id
        if isinstance(target, ast.Attribute) \
                and target.attr in reg.FENCED_STATE:
            return target.attr
        return None

    def _check_fence(self, node: ast.AST, targets: Sequence[ast.AST],
                     value: ast.AST, aug_add: bool = False) -> None:
        if self.is_test or not self.traced:
            return
        name = next((n for n in map(self._fenced_target, targets) if n),
                    None)
        if name is None:
            return
        hazard = (_contains_mul_add(value) if not aug_add
                  else any(isinstance(m, ast.BinOp)
                           and isinstance(m.op, ast.Mult)
                           for m in ast.walk(value)))
        if not hazard:
            return
        if _sanctioned_here(self.ctx, self.fi, self.stack) is not None:
            return
        self._emit(node, "NUM005",
                   f"unfenced mul+add update of fenced state '{name}': "
                   f"XLA contracts producer/consumer mul+add chains "
                   f"into FMAs with fusion-dependent last-ulp rounding "
                   f"— use the fence discipline (optimization_barrier "
                   f"+ pre-scaled .at[].add; see reduction_registry."
                   f"FENCE_CONTEXTS)")

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_fence(node, node.targets, node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.op, ast.Add):
            self._check_fence(node, [node.target], node.value,
                              aug_add=True)
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# file rules
# ---------------------------------------------------------------------------
def rule_file_walk(fi: FileInfo, ctx: NumContext) -> List[Finding]:
    w = _Walker(fi, ctx)
    w.visit(fi.tree)
    return w.findings


FILE_RULES = (rule_file_walk,)


# ---------------------------------------------------------------------------
# project rule: NUM000 registry soundness
# ---------------------------------------------------------------------------
_REG_REL = "tools/numcheck/reduction_registry.py"
_TOL_REL = "tools/numcheck/tolerance_registry.py"


def rule_registry_sound(ctx: NumContext) -> List[Finding]:
    out: List[Finding] = []

    def bad(rel: str, msg: str) -> None:
        out.append(Finding(rel, 1, "NUM000", msg))

    for table, kind in ((reg.REDUCERS, "reducer"),
                        (reg.CONTEXTS, "context"),
                        (reg.FENCE_CONTEXTS, "fence context"),
                        (reg.COMPENSATED, "compensation idiom")):
        for d in table:
            func = d.get("function") or d.get("name")
            module = d.get("module", "")
            if not func or not module:
                bad(_REG_REL, f"{kind} entry {d!r} missing "
                              f"function/module")
                continue
            if not d.get("why", "").strip():
                bad(_REG_REL, f"{kind} '{func}' has no justification")
            path = os.path.join(ctx.root, module)
            analyzed = [rel for rel in ctx.defined_funcs
                        if _module_matches(rel, module)]
            if analyzed:
                if not any(func in ctx.defined_funcs[rel]
                           for rel in analyzed):
                    bad(_REG_REL,
                        f"{kind} '{func}' is not defined in {module}: "
                        f"the registry drifted from the code")
            elif not os.path.exists(path):
                bad(_REG_REL, f"{kind} '{func}' names missing module "
                              f"{module}")
    for name, row in tolreg.TOLERANCES.items():
        if not isinstance(row.get("value"), (int, float)):
            bad(_TOL_REL, f"tolerance '{name}' has a non-numeric value")
        for key in ("why", "contract", "unit"):
            if not str(row.get(key, "")).strip():
                bad(_TOL_REL, f"tolerance '{name}' missing '{key}'")
    return out


PROJECT_RULES = (rule_registry_sound,)
