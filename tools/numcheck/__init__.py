"""numcheck — numeric-reproducibility discipline analyzer.

The sixth static gate (after tpulint, spmdcheck, memcheck, detcheck,
concheck), aimed at the floating-point hazards the byte-identity
contract rests on: reassociation-unsafe reductions over persistent
state (the PR 14 bug class), uncompensated wide-to-narrow casts,
float ``==`` outside digest identity, unregistered tolerance magic
constants, and unfenced mul+add score updates (the FMA-contraction
lesson).  Rules NUM000-NUM005 (see ``rules.py``) run as a tier-1 gate
via ``tests/test_numcheck.py`` / ``python -m tools.check`` and by
hand::

    python -m tools.numcheck [--update-baseline] [paths...]

Shares the analyzer plumbing in ``tools/analysis_core.py`` (one AST
parse per file per process, ``# numcheck: disable=NUMxxx -- why``
suppressions, content-keyed baseline — committed EMPTY).  The
declarative contract lives in ``reduction_registry.py`` (canonical
reducers, sanctioned partition-independent contexts, fence helpers,
compensation idioms) and ``tolerance_registry.py`` (every named
comparison budget).  The RUNTIME half is the ulp contract
(``lightgbm_tpu/obs/num_contract.py``, ``LGBM_TPU_NUM_CONTRACT=1``)
and the cross-partition identity harness
(``tools/identity_check.py``); this package only analyzes source.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from tools.analysis_core import (FileInfo, Finding, discover_files,
                                 load_baseline, new_findings, suppressed,
                                 write_baseline)

from .rules import FILE_RULES, PROJECT_RULES, RULE_TITLES, build_context

BASELINE_DEFAULT = os.path.join("tools", "numcheck", "baseline.json")

__all__ = [
    "run_numcheck", "Finding", "RULE_TITLES", "load_baseline",
    "write_baseline", "new_findings", "BASELINE_DEFAULT",
]


def run_numcheck(paths: Sequence[str] = ("lightgbm_tpu",),
                 root: Optional[str] = None,
                 project_rules: bool = True,
                 ) -> Tuple[List[Finding], Dict[str, FileInfo]]:
    """Analyze ``paths``; returns (findings sorted by location, FileInfo
    by relative path).  Inline suppressions applied; the baseline is NOT
    — callers diff via :func:`new_findings` (same contract as the other
    five analyzers).  ``project_rules=False`` skips the registry-
    soundness project rule for fixture runs.  Analyzer-fixture
    directories (``*_fixtures``) are skipped: their files are
    deliberate hazards for OTHER analyzers' tests and would flood the
    tolerance sweep when numcheck covers ``tests/``."""
    root = os.path.abspath(root or os.getcwd())
    files = [fi for fi in discover_files(paths, root)
             if "_fixtures" not in os.path.dirname(fi.rel)]
    ctx = build_context(files, root, project_rules=project_rules)
    findings: List[Finding] = []
    for fi in files:
        for rule in FILE_RULES:
            for f in rule(fi, ctx):
                if not suppressed(fi, f):
                    findings.append(f)
    if project_rules:
        for rule in PROJECT_RULES:
            findings.extend(rule(ctx))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings, ctx.by_rel
