"""numcheck CLI: ``python -m tools.numcheck [options] [paths...]``.

Exit codes mirror the other analyzers: 0 = clean vs baseline, 1 = new
findings, 2 = usage error.  Output is ``file:line: RULE message``.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import (BASELINE_DEFAULT, load_baseline, new_findings,
               run_numcheck, write_baseline)


def _dump_registry() -> int:
    """Human-readable dump of the numeric ground truth: canonical
    reducers, sanctioned raw-reduction contexts, fence contexts, and
    the named tolerance table (mirrors concheck --lockgraph)."""
    from . import reduction_registry as reg
    from . import tolerance_registry as tols
    print("canonical reducers (order-pinned reduction discipline):")
    for r in reg.REDUCERS:
        print(f"  {r['module']}::{r['name']}\n      {r['why']}")
    print("sanctioned raw-reduction contexts (partition-independent):")
    for c in reg.CONTEXTS:
        print(f"  {c['module']}::{c['function']}\n      {c['why']}")
    print("fenced score-update contexts:")
    for c in reg.FENCE_CONTEXTS:
        print(f"  {c['module']}::{c['function']}\n      {c['why']}")
    print(f"psum combine seams: {', '.join(sorted(reg.PSUM_FUNCS))}")
    print(f"tolerances ({len(tols.TOLERANCES)} named budgets):")
    width = max(len(n) for n in tols.TOLERANCES)
    for name, row in tols.TOLERANCES.items():
        print(f"  {name:<{width}}  {row['value']:<8g} {row['unit']:<8}"
              f" {row['contract']}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.numcheck",
        description="numeric-reproducibility analyzer for lightgbm_tpu "
                    "(rules NUM000-NUM005; see README 'Static "
                    "analysis')")
    parser.add_argument("paths", nargs="*", default=["lightgbm_tpu"],
                        help="files/directories to analyze "
                             "(default: lightgbm_tpu)")
    parser.add_argument("--root", default=None,
                        help="project root (default: cwd)")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help=f"baseline file (default: {BASELINE_DEFAULT} "
                             f"under --root)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, pinned or not")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to pin the current "
                             "findings, then exit 0")
    parser.add_argument("--no-project-rules", action="store_true",
                        help="skip the registry-soundness project rule")
    parser.add_argument("--registry", action="store_true",
                        help="dump the sanctioned-reduction contexts and "
                             "the named tolerance table, then exit 0")
    args = parser.parse_args(argv)

    if args.registry:
        return _dump_registry()

    root = os.path.abspath(args.root or os.getcwd())
    baseline_path = (os.path.abspath(args.baseline) if args.baseline
                     else os.path.join(root, BASELINE_DEFAULT))
    try:
        findings, by_rel = run_numcheck(
            args.paths or ["lightgbm_tpu"], root=root,
            project_rules=not args.no_project_rules)
    except OSError as exc:
        print(f"numcheck: {exc}", file=sys.stderr)
        return 2

    if args.update_baseline:
        write_baseline(baseline_path, findings, by_rel,
                       tool="tools.numcheck")
        print(f"numcheck: baseline updated with {len(findings)} "
              f"finding(s) at {os.path.relpath(baseline_path, root)}")
        return 0

    baseline = ({} if args.no_baseline
                else load_baseline(baseline_path))
    fresh = new_findings(findings, by_rel, baseline)
    for f in fresh:
        print(f.render())
    pinned = len(findings) - len(fresh)
    if fresh:
        print(f"numcheck: {len(fresh)} new finding(s)"
              + (f" ({pinned} baselined)" if pinned else "")
              + "; fix them, suppress with justification "
                "(# numcheck: disable=NUMxxx -- why), or refresh the "
                "baseline with --update-baseline")
        return 1
    print(f"numcheck: clean ({pinned} baselined finding(s), "
          f"{len(by_rel)} file(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
