"""Partition-registry completeness gate — the memcheck-style wall for
device placement (ISSUE 11 satellite).

``tools/memcheck`` proves statically that no dispatch exceeds the HBM
budget; this gate proves that every PERSISTENT array name the system
can place on a mesh matches **exactly one** partition rule in
``lightgbm_tpu/parallel/partition.py`` — an unmatched name is a hard
error (the runtime ``match_name`` raises the same way), and an
AMBIGUOUS name (two overlapping rules) fails here before it can make
two placement sites disagree about a layout.

The audited name set is derived from the REAL ``DeviceData`` and
``ServePack`` NamedTuple fields plus the booster-level state names
(``persistent_names``), so a newly added persistent field is audited
automatically — it either matches a rule or turns this gate red.

Checked contexts: data/voting (row-sharded), feature (replicated
rows), and the serve rule table on its own.  Exit 1 on any finding;
``file:rule`` style output mirrors the other analyzers.

Usage::

    python -m tools.partition_audit            # gate (exit 1 on red)
    python -m tools.partition_audit --table    # print the rule table
"""
from __future__ import annotations

import sys


def run_audit() -> list:
    """-> findings (empty == clean).  Imports the live registry so the
    audit can never drift from the shipped rules."""
    from lightgbm_tpu.parallel.partition import (audit_rules,
                                                 persistent_names,
                                                 serve_rules, train_rules)
    findings = []
    names = persistent_names(num_valid=2)
    for label, rules in (
            ("train[row-sharded]", train_rules("data", True)),
            ("train[replicated-rows]", train_rules("data", False))):
        for f in audit_rules(rules, names):
            findings.append(f"PARTITION001 {label}: {f}")
    serve_names = [n for n in names if n.startswith("serve/")]
    for f in audit_rules(serve_rules(), serve_names):
        findings.append(f"PARTITION001 serve: {f}")
    return findings


def rule_table() -> str:
    from lightgbm_tpu.parallel.partition import train_rules
    lines = ["rule            regex                     spec (data/voting)"]
    for name, rx, spec in train_rules("data", True):
        lines.append(f"{name:<15} {rx:<25} {spec}")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--table" in argv:
        print(rule_table())
        return 0
    findings = run_audit()
    for f in findings:
        print(f)
    if findings:
        print(f"partition_audit: {len(findings)} finding(s)")
        return 1
    print("partition_audit: clean (every persistent name matches "
          "exactly one rule)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
