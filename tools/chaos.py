"""Chaos harness — SIGKILL a rank mid-train, demand byte-identity back.

The elastic protocol's headline guarantee (ISSUE: "byte-identical
recovery") is only credible against a REAL dead process: a thread-level
fake cannot die between a barrier commit's shard publish and its
manifest, and cannot leave a half-written socket.  This launcher:

1. hosts an :class:`~lightgbm_tpu.parallel.elastic.ElasticCoordinator`
   in-process,
2. spawns N worker processes (``python -m tools.chaos --worker spec``)
   that build the SAME synthetic dataset from the spec's seed and train
   it through :func:`~lightgbm_tpu.boosting.streaming.train_elastic`,
3. watches worker progress through the coordinator's heartbeat detail
   (``membership()``) and delivers ``SIGKILL`` — not SIGTERM; no atexit, no
   flushes — to the victim the moment it reports the kill iteration,
4. optionally respawns a replacement joiner (regrow coverage),
5. trains the uninterrupted single-process oracle in-parent with the
   same protocol shard count ``S``, and
6. exits nonzero unless EVERY surviving worker's final model text
   sha256 AND score digest equal the oracle's.

Because the identity domain is ``(data, config, S)`` — never the world
size or membership history (``boosting/streaming.py`` module docstring)
— the single-process oracle doubles as the any-world oracle: a clean
2-process run, a killed-and-shrunk run, and a killed-and-regrown run
must all land on the oracle's bytes.

Usage (the tier-1 gate runs the toy shape; bench's ``elastic`` leg
re-uses :func:`run_chaos` programmatically)::

    python -m tools.chaos --workers 2 --kill-iter 3            # shrink
    python -m tools.chaos --workers 2 --kill-iter 3 --respawn  # regrow
    python -m tools.chaos --workers 2 --no-kill                # control
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shared spec -> (params, dataset): the parent's oracle and every worker
# must construct bitwise-identical inputs from the spec alone
# ---------------------------------------------------------------------------
def default_spec(rundir: str, workers: int = 2, shards: int = 0,
                 iters: int = 8, rows: int = 600, features: int = 8,
                 leaves: int = 7, snapshot_freq: int = 1,
                 seed: int = 7) -> Dict[str, Any]:
    return {
        "rows": int(rows), "features": int(features), "seed": int(seed),
        "shards": int(shards) or int(workers),
        "params": {
            "objective": "regression", "num_leaves": int(leaves),
            "num_iterations": int(iters), "learning_rate": 0.2,
            "min_data_in_leaf": 5, "feature_fraction": 0.8, "seed": 3,
            "snapshot_freq": int(snapshot_freq), "snapshot_keep": 2,
            "output_model": os.path.join(rundir, "chaos_model.txt"),
            "verbose": -1,
        },
    }


def build_inputs(spec: Dict[str, Any]):
    """spec -> (params, BinnedDataset).  Pure function of the spec."""
    import numpy as np

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset, Metadata

    rng = np.random.default_rng(spec["seed"])
    n, f = spec["rows"], spec["features"]
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + np.sin(X[:, 2])
         + rng.normal(scale=0.1, size=n))
    params = dict(spec["params"])
    md = Metadata()
    md.set_field("label", y.astype(np.float32))
    ds = BinnedDataset.from_raw(X, Config.from_params(dict(params)),
                                metadata=md)
    return params, ds


def _model_identity(booster) -> Dict[str, str]:
    import hashlib
    text = booster.save_model_to_string(-1)
    return {"model_sha256": hashlib.sha256(text.encode()).hexdigest(),
            "digest": booster.digest()}


# ---------------------------------------------------------------------------
# worker mode
# ---------------------------------------------------------------------------
def worker_main(spec_path: str) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with open(spec_path) as f:
        spec = json.load(f)

    from lightgbm_tpu.boosting.streaming import StreamTrainer, train_elastic

    # iteration floor: at toy shape a warm-cache worker can burn through
    # every iteration between two heartbeats, closing the kill window
    # before the launcher ever sees the victim's progress.  The throttle
    # (a sleep, identity-neutral) guarantees each reported iteration is
    # observable, so the SIGKILL lands at the REQUESTED iteration.
    slow = float(os.environ.get("LGBM_TPU_CHAOS_ITER_SLEEP_S", "0") or 0)
    if slow > 0:
        orig_iter = StreamTrainer._train_one_iter

        def throttled(self, it):
            time.sleep(slow)
            return orig_iter(self, it)

        StreamTrainer._train_one_iter = throttled

    params, ds = build_inputs(spec)
    booster = train_elastic(params, ds, num_shards=spec["shards"],
                            min_world=int(spec.get("min_world", 1)))
    member = os.environ.get("LGBM_TPU_ELASTIC_MEMBER", f"pid{os.getpid()}")
    result = dict(_model_identity(booster), member=member)
    # MTTR accounting (ISSUE 17): episodes are recorded module-side
    # whether or not tracing is on, so every survivor reports how long
    # each recovery it lived through took, phase by phase
    from lightgbm_tpu.obs import fleet
    result["episodes"] = fleet.recovery_episodes()
    out = os.path.join(os.path.dirname(spec_path), f"result-{member}.json")
    with open(out + ".tmp", "w") as f:
        json.dump(result, f)
    os.replace(out + ".tmp", out)
    print(f"[chaos-worker {member}] OK {result['model_sha256'][:12]}")
    return 0


# ---------------------------------------------------------------------------
# launcher
# ---------------------------------------------------------------------------
def _spawn(rundir: str, spec_path: str, address: str,
           member: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "LGBM_TPU_ELASTIC": address,
        "LGBM_TPU_ELASTIC_MEMBER": member,
        "LGBM_TPU_HEARTBEAT_S": env.get("LGBM_TPU_HEARTBEAT_S", "0.1"),
        "LGBM_TPU_CHAOS_ITER_SLEEP_S":
            env.get("LGBM_TPU_CHAOS_ITER_SLEEP_S", "0.25"),
        "LGBM_TPU_COLLECTIVE_DEADLINE_S":
            env.get("LGBM_TPU_COLLECTIVE_DEADLINE_S", "60"),
        "PYTHONPATH": _REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    log = open(os.path.join(rundir, f"log-{member}.txt"), "w")
    return subprocess.Popen(
        [sys.executable, "-m", "tools.chaos", "--worker", spec_path],
        cwd=_REPO, env=env, stdout=log, stderr=subprocess.STDOUT)


def run_chaos(workers: int = 2, shards: int = 0, iters: int = 8,
              rows: int = 600, features: int = 8, leaves: int = 7,
              snapshot_freq: int = 1, kill_iter: Optional[int] = 3,
              kill_member: int = 1, respawn: bool = False,
              rundir: Optional[str] = None,
              timeout_s: float = 420.0) -> Dict[str, Any]:
    """One chaos scenario end-to-end; returns the verdict dict (key
    ``ok``).  ``kill_iter=None`` is the uninterrupted control run."""
    from lightgbm_tpu.boosting.streaming import StreamTrainer
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel.elastic import ElasticCoordinator

    rundir = rundir or tempfile.mkdtemp(prefix="lgbm_tpu_chaos_")
    spec = default_spec(rundir, workers=workers, shards=shards,
                        iters=iters, rows=rows, features=features,
                        leaves=leaves, snapshot_freq=snapshot_freq)
    spec["min_world"] = workers
    spec_path = os.path.join(rundir, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f, indent=1)

    # the single-process oracle at the same protocol shard count: the
    # identity every run — any world, any kill — must reproduce
    params, ds = build_inputs(spec)
    oracle_params = dict(params, snapshot_freq=-1)
    oracle = StreamTrainer(Config.from_params(oracle_params), ds,
                           num_shards=spec["shards"]).train()
    want = _model_identity(oracle)

    coord = ElasticCoordinator(heartbeat_timeout_s=1.0)
    address = coord.start()
    procs: Dict[str, subprocess.Popen] = {}
    verdict: Dict[str, Any] = {
        "ok": False, "rundir": rundir, "oracle": want, "killed": None,
        "respawned": None, "results": [], "errors": [],
    }
    try:
        for i in range(workers):
            member = f"worker-{i}"
            procs[member] = _spawn(rundir, spec_path, address, member)

        victim = f"worker-{kill_member}" if kill_iter is not None else None
        deadline = time.monotonic() + timeout_s
        respawned = 0
        while time.monotonic() < deadline:
            info = coord.membership()
            if victim is not None and victim in procs:
                mem = next((m for m in info["members"]
                            if m["member"] == victim), None)
                if mem is not None and \
                        int(mem["detail"].get("iteration", 0)) >= kill_iter:
                    os.kill(procs[victim].pid, signal.SIGKILL)
                    procs[victim].wait()
                    verdict["killed"] = {
                        "member": victim,
                        "at_iteration": mem["detail"].get("iteration"),
                        "generation": info["generation"]}
                    print(f"[chaos] SIGKILL {victim} at iteration "
                          f"{mem['detail'].get('iteration')} "
                          f"(generation {info['generation']})")
                    del procs[victim]
                    victim = None
                    if respawn:
                        member = f"joiner-{respawned}"
                        respawned += 1
                        # the replacement joins with min_world=1: it
                        # must merge into the live world, not gate on
                        # the original formation size
                        jspec = dict(spec, min_world=1)
                        jpath = os.path.join(rundir, "spec-joiner.json")
                        with open(jpath, "w") as f:
                            json.dump(jspec, f, indent=1)
                        procs[member] = _spawn(rundir, jpath, address,
                                               member)
                        verdict["respawned"] = member
            if procs and all(p.poll() is not None for p in procs.values()):
                break
            time.sleep(0.05)
        else:
            verdict["errors"].append(f"timeout after {timeout_s}s")

        for member, proc in procs.items():
            rc = proc.poll()
            if rc is None:
                proc.kill()
                proc.wait()
                verdict["errors"].append(f"{member} hung; killed")
            elif rc != 0:
                verdict["errors"].append(f"{member} exited {rc}")
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
        coord.stop()

    for name in sorted(os.listdir(rundir)):
        if name.startswith("result-") and name.endswith(".json"):
            with open(os.path.join(rundir, name)) as f:
                verdict["results"].append(json.load(f))
    if not verdict["results"]:
        verdict["errors"].append("no worker produced a result")
    for res in verdict["results"]:
        for key in ("model_sha256", "digest"):
            if res[key] != want[key]:
                verdict["errors"].append(
                    f"{res['member']} {key} mismatch: {res[key][:12]} != "
                    f"oracle {want[key][:12]}")

    # MTTR verdict (ISSUE 17): a killed run must leave at least one
    # survivor-recorded recovery episode whose phases sum to mttr_s;
    # the slowest episode becomes THE headline number for the run
    episodes = [dict(ep, member=res["member"])
                for res in verdict["results"]
                for ep in res.get("episodes", [])]
    for ep in episodes:
        gap = abs(sum(ep["phases"].values()) - ep["mttr_s"])
        if gap > 1e-9:
            verdict["errors"].append(
                f"{ep['member']} episode phases sum "
                f"{sum(ep['phases'].values()):.6f}s != mttr "
                f"{ep['mttr_s']:.6f}s")
    if verdict["killed"] is not None and verdict["results"] \
            and not episodes:
        verdict["errors"].append(
            "rank was killed but no survivor recorded a recovery "
            "episode")
    if episodes:
        top = max(episodes, key=lambda ep: ep["mttr_s"])
        verdict["recovery"] = top
        verdict["mttr_s"] = top["mttr_s"]
    verdict["ok"] = not verdict["errors"]
    return verdict


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", metavar="SPEC", help=argparse.SUPPRESS)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--shards", type=int, default=0,
                    help="protocol shard count (default: --workers)")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--rows", type=int, default=600)
    ap.add_argument("--features", type=int, default=8)
    ap.add_argument("--leaves", type=int, default=7)
    ap.add_argument("--snapshot-freq", type=int, default=1)
    ap.add_argument("--kill-iter", type=int, default=3,
                    help="SIGKILL the victim when it reports this "
                         "iteration")
    ap.add_argument("--kill-member", type=int, default=1)
    ap.add_argument("--no-kill", action="store_true",
                    help="uninterrupted control run")
    ap.add_argument("--respawn", action="store_true",
                    help="spawn a replacement joiner after the kill")
    ap.add_argument("--rundir")
    ap.add_argument("--timeout", type=float, default=420.0)
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.worker:
        return worker_main(args.worker)

    verdict = run_chaos(
        workers=args.workers, shards=args.shards, iters=args.iters,
        rows=args.rows, features=args.features, leaves=args.leaves,
        snapshot_freq=args.snapshot_freq,
        kill_iter=None if args.no_kill else args.kill_iter,
        kill_member=args.kill_member, respawn=args.respawn,
        rundir=args.rundir, timeout_s=args.timeout)
    if args.as_json:
        print(json.dumps(verdict, indent=1))
    else:
        for err in verdict["errors"]:
            print(f"[chaos] FAIL: {err}")
        mttr = verdict.get("mttr_s")
        mttr_txt = f", mttr={mttr:.3f}s" if mttr is not None else ""
        print(f"[chaos] {'OK' if verdict['ok'] else 'FAILED'}: "
              f"{len(verdict['results'])} result(s), killed="
              f"{verdict['killed']}{mttr_txt}, oracle "
              f"{verdict['oracle']['model_sha256'][:12]}")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
