"""One-command cross-partition identity harness:
``python -m tools.identity_check``.

The executable form of the byte-identity contract (PR 11/14/16):
training is a pure function of ``(data, config, S)`` where ``S`` is
the protocol shard count — NEVER of how those shards are scheduled,
fused, streamed, or which members computed them.  One toy workload is
trained across the full partition matrix and the digest law is
asserted within each shard-count group:

* ``S=1`` — ``serial`` (in-memory fused path) and ``stream1`` (the
  streamed trainer over the same resident bytes);
* ``S=2`` — ``mesh2`` (in-memory 2-shard data-parallel mesh),
  ``mesh2_block0`` (the same mesh under the ``LGBM_TPU_MESH_BLOCK=0``
  per-iteration escape hatch), ``stream2`` (streamed 2-shard), and
  ``elastic1`` (the elastic protocol at world 1 pinned to ``S=2``);
* ``S=1·pallas`` / ``S=1·compact`` — the ISSUE 20 streamed-kernel
  groups: ``serial_<backend>`` (in-memory monolithic kernel) vs
  ``stream1_<backend>`` (accumulator-seeded per-block kernel folds),
  both force-run on CPU through the auto-interpret path.  These are
  SEPARATE groups: the quantized kernel histograms legitimately
  differ in value from the exact scatter backend, so the law is
  identity within a forced backend, never across backends.

(Serial and 2-shard models legitimately differ: per-shard partials
combine through the psum seam in a different — but partition-pinned —
order.  The law is identity WITHIN a shard count, which is exactly
what elastic recovery and streamed restarts rely on.)

Every scenario runs with the determinism ledger armed
(``LGBM_TPU_DETERMINISM=1``); a violation is reported as the FIRST
diverging scenario pair and window, the localization a real
reassociation bug needs.  The ulp contract
(``LGBM_TPU_NUM_CONTRACT=1``, ``obs/num_contract.py``) rides along:
any window whose canonical-vs-f64-oracle drift trips the registered
``score_root_ulp`` budget fails the run too.

``--drift-proof`` proves the wall trips on the PR 14 bug class: a
child process re-execs the ``S=1`` group with the ``num.reassoc``
fault armed from the environment (``utils/faults.py`` — the canonical
chunk+pairwise root reducer silently reverts to a raw ``jnp.sum``;
env-armed because jit resolves the flag at trace time).  The fused
in-memory program and the streamed per-block programs then accumulate
in different orders, the digest law breaks, and the harness must exit
nonzero naming the diverging pair — while ``tools/numcheck``'s NUM001
flags the same hazard statically at file:line.

Usage::

    python -m tools.identity_check [--scenarios serial,stream1,...]
                                   [--rows 600] [--rounds 6]
                                   [--drift-proof] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("LGBM_TPU_DETERMINISM", "1")
os.environ.setdefault("LGBM_TPU_NUM_CONTRACT", "1")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    # the whole matrix runs in ONE process: the mesh scenarios need a
    # 2-device pool, fixed before jax initializes
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2"
                               ).strip()

import numpy as np

# scenario -> shard-count group; identity is asserted WITHIN a group
MATRIX: Dict[str, str] = {
    "serial": "S=1",
    "stream1": "S=1",
    "mesh2": "S=2",
    "mesh2_block0": "S=2",
    "stream2": "S=2",
    "elastic1": "S=2",
    "serial_pallas": "S=1·pallas",
    "stream1_pallas": "S=1·pallas",
    "serial_compact": "S=1·compact",
    "stream1_compact": "S=1·compact",
}

BASE_PARAMS = {"objective": "binary", "num_leaves": 7,
               "min_data_in_leaf": 5, "verbose": -1, "output_freq": 2,
               "learning_rate": 0.2}


def _toy_data(rows: int, f: int = 6, seed: int = 7):
    """Synthetic binary data, pure in ``seed`` (counter-based Philox —
    the harness itself must satisfy its own contract)."""
    gen = np.random.Generator(np.random.Philox(key=[seed, 0]))
    X = gen.normal(size=(rows, f)).astype(np.float32)
    noise = np.random.Generator(np.random.Philox(key=[seed, 1])).normal(
        size=rows)
    y = (X[:, 0] + 0.5 * noise > 0).astype(np.float64)
    return X, y


def _resident(X, y, params):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset, Metadata
    cfg = Config.from_params(dict(params))
    md = Metadata()
    md.set_field("label", y)
    return cfg, BinnedDataset.from_raw(X, cfg, metadata=md)


def run_once(scenario: str, rows: int, rounds: int) -> Dict:
    """Train one scenario; -> {"ledger": {window_it: digest}, "final":
    digest, "num_trips": [...], "num_ledger": [...]}."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.streaming import (StreamTrainer,
                                                 train_elastic)
    from lightgbm_tpu.obs import determinism, num_contract
    determinism.reset()
    num_contract.reset()
    X, y = _toy_data(rows)
    params = {**BASE_PARAMS, "num_iterations": rounds}
    # ISSUE 20 streamed-kernel scenarios: "<base>_<backend>" forces the
    # histogram backend on BOTH sides of the pair (env save/restored);
    # compact additionally drops its slot threshold and deepens the
    # tree so the tail wave actually selects the compact kernel
    base, fenv = scenario, {}
    for suf in ("_pallas", "_compact"):
        if scenario.endswith(suf):
            base, bk = scenario[:-len(suf)], suf[1:]
            fenv = {"LGBM_TPU_HIST_BACKEND": bk}
            if bk == "compact":
                fenv["LGBM_TPU_COMPACT_SLOTS"] = "4"
                params["num_leaves"] = 15
    saved = {k: os.environ.get(k) for k in fenv}
    os.environ.update(fenv)
    try:
        return _run_base(base, scenario, X, y, params, fenv)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _run_base(base: str, scenario: str, X, y, params, fenv) -> Dict:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.streaming import (StreamTrainer,
                                                 train_elastic)
    from lightgbm_tpu.obs import determinism, num_contract
    if base in ("mesh2", "mesh2_block0"):
        params.update({"tree_learner": "data", "mesh_shape": [2]})
    if base in ("serial", "mesh2", "mesh2_block0"):
        block0 = base == "mesh2_block0"
        old = os.environ.get("LGBM_TPU_MESH_BLOCK")
        if block0:
            os.environ["LGBM_TPU_MESH_BLOCK"] = "0"
        try:
            gbdt = lgb.train(params, lgb.Dataset(X, label=y,
                                                 params=params))._gbdt
        finally:
            if block0:
                if old is None:
                    os.environ.pop("LGBM_TPU_MESH_BLOCK", None)
                else:
                    os.environ["LGBM_TPU_MESH_BLOCK"] = old
    elif base in ("stream1", "stream2"):
        cfg, res = _resident(X, y, params)
        shards = 2 if base == "stream2" else 0
        tr = StreamTrainer(cfg, res, num_shards=shards)
        if fenv:
            assert tr.backend == fenv["LGBM_TPU_HIST_BACKEND"], \
                f"{scenario}: forced backend not engaged ({tr.backend})"
        gbdt = tr.train()
    elif base == "elastic1":
        from lightgbm_tpu.parallel.elastic import (ElasticClient,
                                                   ElasticCoordinator)
        cfg, res = _resident(X, y, params)
        coord = ElasticCoordinator(heartbeat_timeout_s=10.0)
        coord.start()
        try:
            client = ElasticClient(coord.address, member="ident0",
                                   deadline_s=10.0,
                                   heartbeat_interval_s=0.1)
            gbdt = train_elastic(params, res, num_shards=2,
                                 client=client)
            client.leave()
            client.close()
        finally:
            coord.stop()
    else:
        raise ValueError(f"unknown scenario {scenario!r}")
    ledger = {int(it): d for it, d in determinism.section()["digests"]}
    return {"ledger": ledger, "final": gbdt.digest(),
            "num_trips": num_contract.trips(),
            "num_ledger": num_contract.ledger()}


def first_pair_divergence(ref_name: str, ref: Dict, name: str, got: Dict
                          ) -> Optional[str]:
    """The failure message for the FIRST diverging (pair, window), or
    None when the pair satisfies the digest law.  Window ledgers are
    compared on COMMON iterations (partitionings sample on different
    window grids: the fused mesh once per fusion block, the streamed
    trainer every iteration)."""
    common = sorted(set(ref["ledger"]) & set(got["ledger"]))
    for it in common:
        if ref["ledger"][it] != got["ledger"][it]:
            return (f"first diverging pair ({ref_name}, {name}) at "
                    f"window it={it}: {ref['ledger'][it][:12]} vs "
                    f"{got['ledger'][it][:12]}")
    if ref["final"] != got["final"]:
        return (f"first diverging pair ({ref_name}, {name}) at final "
                f"model: {ref['final'][:12]} vs {got['final'][:12]}")
    return None


def check_matrix(scenarios: List[str], rows: int, rounds: int
                 ) -> Tuple[bool, List[str]]:
    results = {s: run_once(s, rows, rounds) for s in scenarios}
    ok = True
    lines: List[str] = []
    for group in dict.fromkeys(MATRIX[s] for s in scenarios):
        members = [s for s in scenarios if MATRIX[s] == group]
        ref = members[0]
        group_ok = True
        for other in members[1:]:
            msg = first_pair_divergence(ref, results[ref], other,
                                        results[other])
            if msg is not None:
                ok = group_ok = False
                lines.append(f"{group}: FAIL — {msg}")
        if group_ok:
            lines.append(f"{group}: OK — {len(members)} partitioning(s) "
                         f"byte-identical "
                         f"({results[ref]['final'][:12]})")
    for s in scenarios:
        for trip in results[s]["num_trips"]:
            ok = False
            lines.append(f"{s}: FAIL — ulp budget trip at window "
                         f"it={trip['window_it']} "
                         f"({trip['drift_ulps']} ulps > "
                         f"{trip['budget']})")
    return ok, lines


def drift_proof(rows: int, rounds: int) -> Tuple[bool, str]:
    """The wall must TRIP: re-exec the S=1 pair in a child with the
    ``num.reassoc`` fault armed from the environment (trace-time flag:
    arming in THIS process would miss already-compiled programs); the
    child must exit nonzero naming a diverging pair."""
    env = dict(os.environ)
    env["LGBM_TPU_FAULTS"] = "num.reassoc:1000000"
    env.pop("XLA_FLAGS", None)        # child re-derives its own pool
    proc = subprocess.run(
        [sys.executable, "-m", "tools.identity_check", "--scenarios",
         "serial,stream1", "--rows", str(rows), "--rounds",
         str(rounds)],
        env=env, capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    named = [ln for ln in proc.stdout.splitlines()
             if "first diverging pair" in ln]
    if proc.returncode == 0 or not named:
        return False, ("drift-proof: FAIL — num.reassoc armed but the "
                       "identity matrix passed: the harness is blind "
                       "to the PR 14 bug class (child rc="
                       f"{proc.returncode})")
    return True, (f"drift-proof: OK — reassociated root reducer "
                  f"localized ({named[0].strip()})")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.identity_check",
        description="cross-partition byte-identity harness (the "
                    "runtime half of numcheck)")
    parser.add_argument("--scenarios", default=",".join(MATRIX))
    parser.add_argument("--rows", type=int, default=600)
    parser.add_argument("--rounds", type=int, default=6)
    parser.add_argument("--drift-proof", action="store_true",
                        help="also prove num.reassoc breaks the digest "
                             "law and is named")
    parser.add_argument("--json", action="store_true",
                        help="emit one machine-readable JSON line")
    args = parser.parse_args(argv)

    wanted = [s.strip() for s in args.scenarios.split(",") if s.strip()]
    bad = [s for s in wanted if s not in MATRIX]
    if bad:
        print(f"identity_check: unknown scenario(s) {bad}",
              file=sys.stderr)
        return 2

    ok, lines = check_matrix(wanted, args.rows, args.rounds)
    for ln in lines:
        print(ln)
    proof_ok = True
    if args.drift_proof:
        proof_ok, msg = drift_proof(args.rows, args.rounds)
        print(msg)
    if args.json:
        print(json.dumps({"identity_check_ok": bool(ok and proof_ok),
                          "scenarios": wanted}))
    if not (ok and proof_ok):
        print("identity_check: FAIL")
        return 1
    print(f"identity_check: ok ({len(wanted)} partitioning(s), "
          f"digest law holds per shard count)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
