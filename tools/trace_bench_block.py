import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import os, sys, time, glob, gzip, json, collections
import numpy as np, jax, jax.numpy as jnp

n = 1_000_000; leaves = 255; max_bin = int(sys.argv[1]) if len(sys.argv) > 1 else 63
rng = np.random.RandomState(0)
X = rng.normal(size=(n, 28)).astype(np.float32)
y = (X[:, 0]*2 + X[:, 1] - X[:, 2] + rng.normal(size=n) > 0).astype(np.float32)
import lightgbm_tpu as lgb
ds = lgb.Dataset(X, label=y, params={"max_bin": max_bin}); ds.construct()
del X
params = {"objective": "binary", "num_leaves": leaves, "max_bin": max_bin,
          "learning_rate": 0.1, "min_data_in_leaf": 20, "verbose": -1}
from lightgbm_tpu.basic import Booster
bst = Booster(params=params, train_set=ds)
bst.update()
bst._gbdt.train_block(4)
jax.block_until_ready(bst._gbdt.scores)
os.makedirs(f"/tmp/jtrace{max_bin}", exist_ok=True)
with jax.profiler.trace(f"/tmp/jtrace{max_bin}"):
    bst._gbdt.train_block(4)
    jax.block_until_ready(bst._gbdt.scores)
print("trace done")
