"""tpulint rules TPL000-TPL007 (TPL008 doc-consistency: doccheck.py).

Each rule is ``rule(fi, ctx) -> [Finding]``; the runner applies inline
suppressions and the baseline afterwards.  Messages carry a fix-it: the
gate should teach the idiom, not just block the merge.

| id     | hazard                                                        |
|--------|---------------------------------------------------------------|
| TPL000 | ``tpulint: disable`` comment without a ``-- reason``          |
| TPL001 | implicit host sync inside traced code (.item(), np.asarray,   |
|        | float()/int()/bool() on array exprs, device_get, iteration)   |
| TPL002 | recompile hazards: non-static scalar/shape params, mutable    |
|        | defaults, jit closure over a mutated module global            |
| TPL003 | dtype creep: np/jnp.float64 in traced code, dtype-less        |
|        | np.array in jax-adjacent modules                              |
| TPL004 | collective primitive call outside a utils/retry wrapper       |
| TPL005 | Pallas kernel module without an interpret-mode oracle test    |
| TPL006 | bare/broad except that swallows errors without logging        |
| TPL007 | bare print( in library code (cli.py/plotting.py allowed)      |

Traced-code scope (TPL001/TPL003) comes from ``callgraph.compute_traced``;
each traced function is scanned over its OWN body only (nested defs are
their own graph nodes), so host wrappers that merely BUILD traced
closures aren't swept in.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set

from .callgraph import FunctionInfo, _callee_name, compute_traced
from .core import FileInfo, Finding

NP_ALIASES = {"np", "numpy", "onp"}
JAX_ALIASES = {"jnp", "jax", "lax", "pl", "pltpu"}

RULE_TITLES = {
    "TPL000": "suppression without justification",
    "TPL001": "implicit host sync in traced code",
    "TPL002": "recompile hazard",
    "TPL003": "dtype creep into device code",
    "TPL004": "unguarded collective",
    "TPL005": "Pallas kernel without interpret-mode oracle",
    "TPL006": "silently swallowed broad except",
    "TPL007": "bare print in library code",
    "TPL008": "README perf figure drifted from BENCH artifact",
}


@dataclass
class LintContext:
    root: str
    files: List[FileInfo]
    by_rel: Dict[str, FileInfo]
    functions: Dict[str, FunctionInfo]
    traced: Set[str]
    project_rules: bool = True


def build_context(files: Sequence[FileInfo], root: str,
                  project_rules: bool = True) -> LintContext:
    functions, traced = compute_traced(files)
    return LintContext(root=root, files=list(files),
                       by_rel={fi.rel: fi for fi in files},
                       functions=functions, traced=traced,
                       project_rules=project_rules)


# -- shared AST helpers ---------------------------------------------------
def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _walk_own(fn_node: ast.AST):
    """Walk a function body EXCLUDING nested def/lambda subtrees."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_jaxish(expr: ast.AST) -> bool:
    """Does the expression contain a jnp./jax./lax. call — i.e. is it an
    array-valued expression rather than Python-scalar bookkeeping?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            if _root_name(node.func) in JAX_ALIASES:
                return True
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"):
                return True
    return False


def _param_names(fn_node: ast.AST) -> Set[str]:
    a = fn_node.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n != "self"}


def _traced_functions(fi: FileInfo, ctx: LintContext) -> List[FunctionInfo]:
    return [info for q, info in ctx.functions.items()
            if q in ctx.traced and info.fi.rel == fi.rel]


# -- TPL000 ---------------------------------------------------------------
def rule_tpl000(fi: FileInfo, ctx: LintContext) -> List[Finding]:
    return [Finding(fi.rel, line, "TPL000",
                    "suppression without justification: add "
                    "`-- <why this hazard is intended>` to the disable "
                    "comment")
            for line in fi.unjustified]


# -- TPL001 ---------------------------------------------------------------
_SYNC_CONVERSIONS = {"float", "int", "bool"}


def rule_tpl001(fi: FileInfo, ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []

    def flag(node: ast.AST, what: str, fix: str) -> None:
        out.append(Finding(fi.rel, node.lineno, "TPL001",
                           f"{what} inside traced code forces a host "
                           f"sync (or fails to trace); {fix}"))

    for info in _traced_functions(fi, ctx):
        params = _param_names(info.node) - info.static_argnames
        for node in _walk_own(info.node):
            if isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute) and func.attr == "item"
                        and not node.args):
                    flag(node, ".item()",
                         "keep the value on device (jnp.where/select on "
                         "the array) or move the read after the block")
                elif (isinstance(func, ast.Attribute)
                      and func.attr in ("asarray", "array")
                      and _root_name(func) in NP_ALIASES):
                    flag(node, f"np.{func.attr}()",
                         "use jnp equivalents in traced code; convert on "
                         "the host side of the jit boundary")
                elif (isinstance(func, ast.Attribute)
                      and func.attr == "device_get"):
                    flag(node, "jax.device_get()",
                         "fetch after the traced block returns")
                elif (isinstance(func, ast.Name)
                      and func.id in _SYNC_CONVERSIONS
                      and len(node.args) == 1 and not node.keywords):
                    arg = node.args[0]
                    if _is_jaxish(arg) or (isinstance(arg, ast.Name)
                                           and arg.id in params):
                        flag(node, f"{func.id}() on an array expression",
                             "keep arithmetic in jnp, or declare the "
                             "argument static if it is a Python scalar")
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                it = node.iter
                if _is_jaxish(it) or (isinstance(it, ast.Name)
                                      and it.id in params):
                    flag(node, "iteration over a traced array",
                         "use lax.scan/fori_loop, or iterate a static "
                         "Python sequence")
    return out


# -- TPL002 ---------------------------------------------------------------
def _mutated_module_globals(fi: FileInfo) -> Set[str]:
    """Module-level names that some function mutates: ``global`` rebinds,
    subscript/attribute stores (``_FLAG[0] = True``), and aug-assigns."""
    module_names: Set[str] = set()
    for node in fi.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    module_names.add(t.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            module_names.add(node.target.id)
    mutated: Set[str] = set()
    for node in ast.walk(fi.tree):
        if isinstance(node, ast.Global):
            mutated.update(n for n in node.names if n in module_names)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    rn = _root_name(t.value if isinstance(t, ast.Attribute)
                                    else t.value)
                    if rn in module_names:
                        mutated.add(rn)
    return mutated


def rule_tpl002(fi: FileInfo, ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    mutated = _mutated_module_globals(fi)
    for info in _traced_functions(fi, ctx):
        if not info.is_root:
            continue
        node = info.node
        a = node.args
        pos_params = list(a.posonlyargs) + list(a.args)
        defaults = list(a.defaults)
        pairs = list(zip(pos_params[len(pos_params) - len(defaults):],
                         defaults))
        pairs += [(p, d) for p, d in zip(a.kwonlyargs, a.kw_defaults)
                  if d is not None]
        for param, dflt in pairs:
            if isinstance(dflt, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(dflt, ast.Call)
                    and _callee_name(dflt.func) in ("list", "dict", "set")):
                out.append(Finding(
                    fi.rel, dflt.lineno, "TPL002",
                    f"mutable default for `{param.arg}` on a traced "
                    f"function: mutation never re-traces; use None + "
                    f"in-body default"))
            elif (info.jit_like
                  and isinstance(dflt, ast.Constant)
                  and isinstance(dflt.value, (int, float, bool))
                  and param.arg not in info.static_argnames):
                out.append(Finding(
                    fi.rel, dflt.lineno, "TPL002",
                    f"jit function takes Python scalar `{param.arg}` "
                    f"not in static_argnames: every distinct value "
                    f"retraces (weak-type permitting); declare it "
                    f"static or pass a jnp scalar"))
        if info.jit_like and mutated:
            seen: Set[str] = set()
            for sub in _walk_own(node):
                if (isinstance(sub, ast.Name) and sub.id in mutated
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id not in seen):
                    seen.add(sub.id)
                    out.append(Finding(
                        fi.rel, sub.lineno, "TPL002",
                        f"jit function closes over module global "
                        f"`{sub.id}` that is mutated elsewhere: the "
                        f"compiled program bakes the traced value in; "
                        f"pass it as an argument or a static cache key"))
    return out


# -- TPL003 ---------------------------------------------------------------
def rule_tpl003(fi: FileInfo, ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    traced_lines: Set[int] = set()
    for info in _traced_functions(fi, ctx):
        for node in _walk_own(info.node):
            if hasattr(node, "lineno"):
                traced_lines.add(node.lineno)
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("float64", "double")
                    and _root_name(node) in (NP_ALIASES | JAX_ALIASES)):
                out.append(Finding(
                    fi.rel, node.lineno, "TPL003",
                    "float64 in traced code: TPU computes f32/bf16 — "
                    "with x64 disabled this silently downcasts, with it "
                    "enabled it recompiles everything wider; use an "
                    "explicit f32 dtype (f64 only host-side)"))
    module_jax = fi.imports_jax()
    for node in ast.walk(fi.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "array"
                and _root_name(node.func) in NP_ALIASES):
            continue
        has_dtype = len(node.args) >= 2 or any(
            kw.arg == "dtype" for kw in node.keywords)
        if has_dtype:
            continue
        if module_jax or node.lineno in traced_lines:
            out.append(Finding(
                fi.rel, node.lineno, "TPL003",
                "dtype-less np.array in a jax-adjacent module defaults "
                "to float64/int64 and drifts when it reaches the device; "
                "state the dtype explicitly"))
    return out


# -- TPL004 ---------------------------------------------------------------
def _is_collective_primitive(call: ast.Call) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr == "process_allgather":
            return "process_allgather"
        if (func.attr == "initialize"
                and isinstance(func.value, ast.Attribute)
                and func.value.attr == "distributed"):
            return "jax.distributed.initialize"
    return None


def rule_tpl004(fi: FileInfo, ctx: LintContext) -> List[Finding]:
    # function names handed to utils/retry (retry_call(f,...)/retrying(f))
    guarded: Set[str] = set()
    for node in ast.walk(fi.tree):
        if (isinstance(node, ast.Call)
                and _callee_name(node.func) in ("retry_call", "retrying")
                and node.args and isinstance(node.args[0], ast.Name)):
            guarded.add(node.args[0].id)

    out: List[Finding] = []

    def visit(node: ast.AST, enclosing: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, child.name)
                continue
            if isinstance(child, ast.Call):
                prim = _is_collective_primitive(child)
                if prim is not None and enclosing not in guarded:
                    out.append(Finding(
                        fi.rel, child.lineno, "TPL004",
                        f"{prim} outside a utils/retry wrapper: a "
                        f"transient DCN/rendezvous fault kills the run; "
                        f"wrap the enclosing function with "
                        f"retry_call/retrying (see io/distributed.py)"))
            visit(child, enclosing)

    visit(fi.tree, None)
    return out


# -- TPL005 ---------------------------------------------------------------
def rule_tpl005(fi: FileInfo, ctx: LintContext) -> List[Finding]:
    if not ctx.project_rules or "pallas_call" not in fi.source:
        return []
    first_line = next(
        (n.lineno for n in ast.walk(fi.tree)
         if isinstance(n, ast.Call)
         and _callee_name(n.func) == "pallas_call"), None)
    if first_line is None:
        return []
    stem = os.path.splitext(fi.basename)[0]
    tests_dir = os.path.join(ctx.root, "tests")
    try:
        test_files = [f for f in os.listdir(tests_dir) if f.endswith(".py")]
    except OSError:
        test_files = []
    for name in test_files:
        try:
            with open(os.path.join(tests_dir, name), encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        if stem in text and "interpret" in text:
            return []
    return [Finding(
        fi.rel, first_line, "TPL005",
        f"Pallas kernel module `{stem}` has no interpret-mode oracle "
        f"test under tests/: add one asserting parity with the XLA "
        f"path (see tests/test_pallas_split.py)")]


# -- TPL006 ---------------------------------------------------------------
_BROAD = {"Exception", "BaseException"}
_HANDLED_CALLS = {
    "log_warning", "log_once", "log_info", "log_error", "log_debug",
    "warn", "warning", "error", "exception", "event", "counter_add",
    "disable_on_compile_error", "fail", "perror", "print_exc",
}


def _handler_is_broad(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def rule_tpl006(fi: FileInfo, ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(fi.tree):
        if not (isinstance(node, ast.ExceptHandler)
                and _handler_is_broad(node)):
            continue
        handled = False
        for sub in node.body:
            for n in ast.walk(sub):
                if isinstance(n, ast.Raise):
                    handled = True
                elif isinstance(n, ast.Call):
                    cn = _callee_name(n.func) or ""
                    if cn in _HANDLED_CALLS or "fallback" in cn:
                        handled = True
        if not handled:
            out.append(Finding(
                fi.rel, node.lineno, "TPL006",
                "broad except swallows errors (including jit/Mosaic "
                "compile failures) silently: log a warning, re-raise, "
                "or route through the pallas_split.py logged-fallback "
                "pattern"))
    return out


# -- TPL007 ---------------------------------------------------------------
_PRINT_ALLOWED = {"cli.py", "plotting.py"}


def rule_tpl007(fi: FileInfo, ctx: LintContext) -> List[Finding]:
    if fi.basename in _PRINT_ALLOWED:
        return []
    return [Finding(
        fi.rel, node.lineno, "TPL007",
        "bare print( in library code: route through utils/log.py "
        "(leveled, rank-prefixed) or obs/ (structured telemetry)")
        for node in ast.walk(fi.tree)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
        and node.func.id == "print"]


FILE_RULES: List[Callable[[FileInfo, LintContext], List[Finding]]] = [
    rule_tpl000, rule_tpl001, rule_tpl002, rule_tpl003, rule_tpl004,
    rule_tpl005, rule_tpl006, rule_tpl007,
]
