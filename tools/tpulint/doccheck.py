"""TPL008: README perf figures vs the latest BENCH_r*.json artifact.

ADVICE r5 item 3 flagged the failure mode by hand: the README quoted
two different with-valid slowdowns and nobody could say which artifact
backed which.  This check mechanizes the detectable slice of that
class: every throughput figure the README quotes as measured
(``NN.N M row-iters/s``) must sit within tolerance of SOME throughput
recorded in the newest parsed ``BENCH_r*.json`` (``value`` /
``full_row_iters_per_sec``).  Run-to-run variance over the device
tunnel is a few percent (README's own caveat), so the tolerance is
15% — the gate catches stale orders-of-magnitude claims after a perf
change, not jitter.

Artifacts whose ``parsed`` is null (driver timeout runs) are skipped;
no parsed artifact at all -> no findings (nothing authoritative to
check against).
"""
from __future__ import annotations

import json
import os
import re
from typing import List

from .core import Finding

_FIGURE_RE = re.compile(r"(\d+(?:\.\d+)?)\s*M\s+row-iters/s")
_TOLERANCE = 0.15


def _latest_bench_throughputs(root: str) -> List[float]:
    """Throughput figures (in M row-iters/s) from the newest BENCH
    artifact that actually parsed."""
    try:
        names = sorted(n for n in os.listdir(root)
                       if re.fullmatch(r"BENCH_r\d+\.json", n))
    except OSError:
        return []
    for name in reversed(names):
        try:
            with open(os.path.join(root, name), encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = data.get("parsed")
        if not isinstance(parsed, dict):
            continue
        vals = [parsed.get(k) for k in ("value", "full_row_iters_per_sec")]
        out = [float(v) / 1e6 for v in vals if isinstance(v, (int, float))]
        if out:
            return out
    return []


def rule_tpl008(root: str) -> List[Finding]:
    readme = os.path.join(root, "README.md")
    try:
        with open(readme, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    bench = _latest_bench_throughputs(root)
    if not bench:
        return []
    out: List[Finding] = []
    in_fence = False
    for lineno, line in enumerate(lines, 1):
        if line.lstrip().startswith("```"):
            in_fence = not in_fence
            continue
        if not in_fence:
            # prose mentions the CPU baseline / target arithmetic by the
            # same unit; only fenced measured-run blocks are claims the
            # artifact must back
            continue
        for m in _FIGURE_RE.finditer(line):
            claimed = float(m.group(1))
            if any(abs(claimed - b) <= _TOLERANCE * b for b in bench):
                continue
            nearest = min(bench, key=lambda b: abs(claimed - b))
            out.append(Finding(
                "README.md", lineno, "TPL008",
                f"README claims {claimed}M row-iters/s but the latest "
                f"parsed BENCH artifact records "
                f"{', '.join(f'{b:.1f}M' for b in bench)} (nearest "
                f"{nearest:.1f}M, >15% off): re-measure or relabel the "
                f"figure with its source run"))
    return out
