"""Traced-code reachability: which functions run under a JAX trace.

TPL001/TPL003 only make sense INSIDE traced code — ``.item()`` in the
host training loop is a deliberate sync, the same call inside a
``jax.jit`` body is a silent per-iteration device round-trip (or a
``TracerArrayConversionError`` on the good days).  The reachability
set is computed in two steps:

1. **Roots** — functions that enter a trace directly: decorated with
   ``@jax.jit`` / ``@functools.partial(jax.jit, ...)``, wrapped via
   ``jax.jit(f)`` / ``shard_map(f, ...)`` / ``pl.pallas_call(f, ...)``,
   or passed as the body of ``lax.scan`` / ``fori_loop`` /
   ``while_loop`` / ``cond`` / ``vmap`` / ``pmap``.
2. **Propagation** — a name-based call-graph walk: every function whose
   bare name is called from a traced function is traced too.  Name
   resolution is deliberately coarse (``self._block_sample`` marks every
   ``_block_sample`` in the package, including subclass overrides —
   which is exactly right for dispatch we can't resolve statically);
   the baseline absorbs the rare over-taint.

Nested ``def``s count as part of their parent's subtree when scanning
(a closure built inside a traced body runs under the same trace), and
are also first-class graph nodes so ``jax.jit(inner)`` marks them.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import FileInfo

# call-wrapping entry points: callee attr/name -> indices of traced args
_WRAP_ARG_POS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,),
    "pjit": (0,),
    "vmap": (0,),
    "pmap": (0,),
    "shard_map": (0,),
    "pallas_call": (0,),
    "scan": (0,),
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "cond": (1, 2),
    "switch": (1, 2, 3, 4),
    "custom_vjp": (0,),
    "checkpoint": (0,),
    "remat": (0,),
}


@dataclass
class FunctionInfo:
    """One def (incl. nested) with what the rules need to know."""
    fi: FileInfo
    node: ast.AST                   # FunctionDef / AsyncFunctionDef
    qualname: str                   # "<rel>::outer.inner"
    name: str                       # bare name
    is_root: bool = False
    jit_like: bool = False          # root via jit/pjit (statics apply)
    static_argnames: Set[str] = field(default_factory=set)
    called: Set[str] = field(default_factory=set)   # bare callee names


def _callee_name(func: ast.AST) -> Optional[str]:
    """Bare name of a call target: ``f(...)`` -> f, ``a.b.f(...)`` -> f."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _static_argnames_from_call(call: ast.Call) -> Set[str]:
    """Parse ``static_argnames=("a", "b")`` out of a jit/partial call."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            out.add(v.value)
        elif isinstance(v, (ast.Tuple, ast.List, ast.Set)):
            for el in v.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
    return out


def _jit_decoration(node: ast.AST) -> Optional[Set[str]]:
    """If ``node`` is jit-decorated, return its static_argnames (possibly
    empty); None when not jit-decorated."""
    for dec in getattr(node, "decorator_list", []):
        # @jax.jit / @jit
        if _callee_name(dec) in ("jit", "pjit"):
            return set()
        if isinstance(dec, ast.Call):
            callee = _callee_name(dec.func)
            if callee in ("jit", "pjit"):               # @jax.jit(...)
                return _static_argnames_from_call(dec)
            if callee == "partial" and dec.args:        # @partial(jax.jit,)
                if _callee_name(dec.args[0]) in ("jit", "pjit"):
                    return _static_argnames_from_call(dec)
    return None


def collect_functions(fi: FileInfo) -> List[FunctionInfo]:
    """All defs in ``fi`` (nested included), with jit-decoration roots
    resolved and bare callee names recorded."""
    out: List[FunctionInfo] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                info = FunctionInfo(fi=fi, node=child,
                                    qualname=f"{fi.rel}::{qual}",
                                    name=child.name)
                statics = _jit_decoration(child)
                if statics is not None:
                    info.is_root = True
                    info.jit_like = True
                    info.static_argnames = statics
                for sub in ast.walk(child):
                    if isinstance(sub, ast.Call):
                        cn = _callee_name(sub.func)
                        if cn is not None:
                            info.called.add(cn)
                out.append(info)
                visit(child, qual)
            else:
                visit(child, prefix)

    visit(fi.tree, "")
    return out


def _mark_wrapped_roots(fi: FileInfo, by_name: Dict[str, List[FunctionInfo]],
                        local_names: Set[str]) -> None:
    """Mark functions passed into jit/scan/shard_map/pallas_call wrappers
    as traced roots (``jax.jit(f)``, ``lax.scan(body, ...)`` ...)."""
    for node in ast.walk(fi.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _callee_name(node.func)
        if callee not in _WRAP_ARG_POS:
            continue
        statics = _static_argnames_from_call(node) if callee in (
            "jit", "pjit") else set()
        for pos in _WRAP_ARG_POS[callee]:
            if pos >= len(node.args):
                continue
            arg = node.args[pos]
            # unwrap functools.partial(f, ...) one level
            if (isinstance(arg, ast.Call)
                    and _callee_name(arg.func) == "partial" and arg.args):
                arg = arg.args[0]
            name = _callee_name(arg)
            if name is None or name not in local_names:
                continue
            for info in by_name.get(name, []):
                if info.fi.rel == fi.rel:
                    info.is_root = True
                    info.static_argnames |= statics
                    if callee in ("jit", "pjit"):
                        info.jit_like = True


def compute_traced(files: Sequence[FileInfo]
                   ) -> Tuple[Dict[str, FunctionInfo], Set[str]]:
    """(all functions by qualname, set of TRACED qualnames)."""
    functions: Dict[str, FunctionInfo] = {}
    by_name: Dict[str, List[FunctionInfo]] = {}
    for fi in files:
        for info in collect_functions(fi):
            functions[info.qualname] = info
            by_name.setdefault(info.name, []).append(info)
    for fi in files:
        _mark_wrapped_roots(fi, by_name, set(by_name))

    traced: Set[str] = set()
    work = [q for q, info in functions.items() if info.is_root]
    while work:
        q = work.pop()
        if q in traced:
            continue
        traced.add(q)
        for callee in functions[q].called:
            for info in by_name.get(callee, []):
                if info.qualname not in traced:
                    work.append(info.qualname)
    return functions, traced
