"""tpulint — JAX/TPU hazard linter + trace-contract checker.

AST-based static analysis for the ``lightgbm_tpu`` package (rules
TPL000-TPL008, see ``rules.py``/``doccheck.py``) run as a tier-1 gate
via ``tests/test_tpulint.py`` and by hand via::

    python -m tools.tpulint [--update-baseline] [paths...]

The companion RUNTIME check — zero post-warmup recompiles on the
training path — lives in ``lightgbm_tpu/obs/trace_contract.py`` (the
library must not import tools/); this package only gates its output.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from .core import (BASELINE_DEFAULT, FileInfo, Finding, count_keys,
                   discover_files, finding_key, load_baseline,
                   new_findings, suppressed, write_baseline)
from .doccheck import rule_tpl008
from .rules import FILE_RULES, RULE_TITLES, build_context

__all__ = [
    "run_lint", "Finding", "RULE_TITLES", "load_baseline",
    "write_baseline", "new_findings", "BASELINE_DEFAULT",
]


def run_lint(paths: Sequence[str] = ("lightgbm_tpu",),
             root: Optional[str] = None,
             project_rules: bool = True,
             ) -> Tuple[List[Finding], Dict[str, FileInfo]]:
    """Lint ``paths`` (files or directories, relative to ``root``).
    Returns (findings sorted by location, FileInfo by relative path).
    Inline suppressions are already applied; the baseline is NOT —
    callers diff via :func:`new_findings`."""
    root = os.path.abspath(root or os.getcwd())
    files = discover_files(paths, root)
    ctx = build_context(files, root, project_rules=project_rules)
    findings: List[Finding] = []
    for fi in files:
        for rule in FILE_RULES:
            for f in rule(fi, ctx):
                if not suppressed(fi, f):
                    findings.append(f)
    if project_rules:
        findings.extend(rule_tpl008(root))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings, ctx.by_rel
