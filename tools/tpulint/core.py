"""Compatibility shim: the analyzer plumbing that lived here through
PRs 3-4 (AST cache, suppressions, content-keyed baseline) moved to
``tools/analysis_core.py`` when memcheck became its third consumer.
Everything re-exports so existing ``from tools.tpulint.core import ...``
sites (spmdcheck, tests) keep working unchanged.
"""
from __future__ import annotations

import os

from tools.analysis_core import (  # noqa: F401 - re-exported surface
    _AST_CACHE, _SUPPRESS_RE, FileInfo, Finding, assert_fixtures_match,
    count_keys, discover_files, expect_markers, finding_key,
    load_baseline, load_file, new_findings, suppressed, write_baseline)

BASELINE_DEFAULT = os.path.join("tools", "tpulint", "baseline.json")
