"""tpulint core: file loading, AST cache, suppressions, baseline.

The linter is a single parse pass per file (ASTs are cached per
``(path, mtime, size)``, shared by every rule — the tier-1 budget is
~10 s for the whole package) plus a set of AST rules (``rules.py``)
and project-level consistency checks (``doccheck.py``).

Suppression contract (documented in README "Static analysis")::

    x = np.array(v)  # tpulint: disable=TPL003 -- host-only text IO path

A disable comment applies to its own line, or — when the line is
comment-only — to the next source line.  A disable WITHOUT a
justification (the ``-- reason`` tail) is itself reported as TPL000:
the whole point of the gate is that every silenced hazard carries its
why in-line.

The baseline (``tools/tpulint/baseline.json``) pins pre-existing
findings so the gate fails only on NEW ones.  Keys are
``file::rule::<stripped source line>`` — line-content keyed, not
line-number keyed, so unrelated edits above a pinned finding don't
break the pin — with a count per key (duplicate identical lines in one
file share a key).
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

BASELINE_DEFAULT = os.path.join("tools", "tpulint", "baseline.json")

# one parse serves both static gates: spmdcheck (tools/spmdcheck) shares
# the suppression syntax under its own tag
_SUPPRESS_RE = re.compile(
    r"#\s*(?:tpulint|spmdcheck):\s*disable="
    r"([A-Za-z0-9_,\s]+?)(?:\s*--\s*(.*))?\s*$")


@dataclass(frozen=True)
class Finding:
    """One hazard: ``file`` is root-relative posix, ``line`` 1-based."""
    file: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} {self.message}"


@dataclass
class FileInfo:
    """A parsed source file plus its per-line suppression map."""
    path: str                       # absolute
    rel: str                        # root-relative, posix separators
    source: str
    lines: List[str]
    tree: ast.Module
    # line -> set of suppressed rule ids ("*" = all)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    # lines whose disable comment carries no justification
    unjustified: List[int] = field(default_factory=list)

    @property
    def basename(self) -> str:
        return os.path.basename(self.rel)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def imports_jax(self) -> bool:
        for node in self.tree.body:
            if isinstance(node, ast.Import):
                if any(a.name.split(".")[0] == "jax" for a in node.names):
                    return True
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "jax":
                    return True
        return False


def _parse_suppressions(fi: FileInfo) -> None:
    for i, raw in enumerate(fi.lines, 1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = (m.group(2) or "").strip()
        # comment-only disable line covers the NEXT source line
        target = i + 1 if raw.strip().startswith("#") else i
        fi.suppressions.setdefault(target, set()).update(rules or {"*"})
        if not reason:
            fi.unjustified.append(i)


# -- AST cache ------------------------------------------------------------
_AST_CACHE: Dict[str, Tuple[Tuple[float, int], FileInfo]] = {}


def load_file(path: str, root: str) -> Optional[FileInfo]:
    """Parse ``path`` (cached on mtime+size); None on syntax errors —
    a file the interpreter itself rejects is not this linter's job."""
    path = os.path.abspath(path)
    try:
        st = os.stat(path)
        stamp = (st.st_mtime, st.st_size)
    except OSError:
        return None
    cached = _AST_CACHE.get(path)
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    if cached is not None and cached[0] == stamp:
        fi = cached[1]
        if fi.rel != rel:           # same file linted under another root
            fi = FileInfo(path, rel, fi.source, fi.lines, fi.tree,
                          fi.suppressions, fi.unjustified)
        return fi
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError):
        return None
    fi = FileInfo(path=path, rel=rel, source=source,
                  lines=source.splitlines(), tree=tree)
    _parse_suppressions(fi)
    _AST_CACHE[path] = (stamp, fi)
    return fi


def discover_files(paths: Sequence[str], root: str) -> List[FileInfo]:
    """Expand files/directories into parsed FileInfos (sorted, deduped)."""
    seen: Dict[str, None] = {}
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        seen[os.path.join(dirpath, name)] = None
        elif p.endswith(".py"):
            seen[os.path.abspath(p)] = None
    out = []
    for path in sorted(seen):
        fi = load_file(path, root)
        if fi is not None:
            out.append(fi)
    return out


def suppressed(fi: FileInfo, finding: Finding) -> bool:
    rules = fi.suppressions.get(finding.line)
    return bool(rules) and ("*" in rules or finding.rule in rules)


# -- baseline -------------------------------------------------------------
def finding_key(f: Finding, fi: Optional[FileInfo]) -> str:
    text = fi.line_text(f.line) if fi is not None else ""
    return f"{f.file}::{f.rule}::{text}"


def count_keys(findings: Sequence[Finding],
               by_rel: Dict[str, FileInfo]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        k = finding_key(f, by_rel.get(f.file))
        counts[k] = counts.get(k, 0) + 1
    return counts


def load_baseline(path: str) -> Dict[str, int]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    entries = data.get("entries", {}) if isinstance(data, dict) else {}
    return {str(k): int(v) for k, v in entries.items()}


def write_baseline(path: str, findings: Sequence[Finding],
                   by_rel: Dict[str, FileInfo]) -> None:
    entries = count_keys(findings, by_rel)
    data = {"version": 1,
            "comment": "pinned pre-existing tpulint findings; refresh "
                       "with `python -m tools.tpulint --update-baseline`",
            "entries": {k: entries[k] for k in sorted(entries)}}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)


def new_findings(findings: Sequence[Finding],
                 by_rel: Dict[str, FileInfo],
                 baseline: Dict[str, int]) -> List[Finding]:
    """Findings beyond the baselined count for their key (oldest-first
    occurrences of a key are considered the pinned ones)."""
    budget = dict(baseline)
    out = []
    for f in findings:
        k = finding_key(f, by_rel.get(f.file))
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out
