"""spmdcheck — cross-rank collective-schedule analyzer.

The static half of the PR-4 desync tooling (the runtime half is the
collective flight recorder, ``lightgbm_tpu/obs/flight_recorder.py``):
AST analysis over the package proving that no code path can make ranks
issue different collective schedules — rules SPM001-SPM004, run as a
tier-1 gate via ``tests/test_spmdcheck.py`` and by hand::

    python -m tools.spmdcheck [--update-baseline] [--schedule] [paths...]

Shares tpulint's parse cache, suppression syntax, and content-keyed
baseline machinery (``tools/tpulint/core.py``); the combined tier-1
static gate parses every file exactly once.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from tools.tpulint.core import (FileInfo, Finding, count_keys,
                                discover_files, load_baseline,
                                new_findings, suppressed, write_baseline)

from .rules import FILE_RULES, RULE_TITLES, SpmdContext, build_context
from .schedule import extract_schedule, schedule_roots

BASELINE_DEFAULT = os.path.join("tools", "spmdcheck", "baseline.json")

__all__ = [
    "run_spmdcheck", "Finding", "RULE_TITLES", "load_baseline",
    "write_baseline", "new_findings", "BASELINE_DEFAULT",
    "render_schedules",
]


def run_spmdcheck(paths: Sequence[str] = ("lightgbm_tpu",),
                  root: Optional[str] = None,
                  ) -> Tuple[List[Finding], Dict[str, FileInfo]]:
    """Analyze ``paths``; returns (findings sorted by location, FileInfo
    by relative path).  Inline suppressions applied; baseline is NOT —
    callers diff via :func:`new_findings` (same contract as tpulint)."""
    root = os.path.abspath(root or os.getcwd())
    files = discover_files(paths, root)
    ctx = build_context(files, root)
    findings: List[Finding] = []
    for fi in files:
        for rule in FILE_RULES:
            for f in rule(fi, ctx):
                if not suppressed(fi, f):
                    findings.append(f)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings, ctx.by_rel


def render_schedules(paths: Sequence[str] = ("lightgbm_tpu",),
                     root: Optional[str] = None) -> List[str]:
    """Human-readable collective schedule per jit/shard_map root and
    host-collective seam function (the ``--schedule`` CLI dump)."""
    root = os.path.abspath(root or os.getcwd())
    files = discover_files(paths, root)
    ctx = build_context(files, root)
    lines: List[str] = []
    for info in schedule_roots(ctx.functions, ctx.traced):
        entries = extract_schedule(info, ctx.functions)
        if not entries:
            continue
        lines.append(f"{info.qualname}:")
        lines.extend(f"  {e.render()}" for e in entries)
    return lines
