"""Collective-schedule extraction — the static half of spmdcheck.

The SPMD contract (reference: every machine executes the identical
split sequence, `data_parallel_tree_learner.cpp:147-162`) translates in
the JAX port to: **every rank must issue the same ordered sequence of
collectives with the same axes and operand shapes**.  GSPMD gets this
for free inside one ``shard_map`` program; the hazard lives in the
Python that *builds* the program (rank-conditional trace-time control
flow) and in the host-collective layer (``io/distributed.py``), where
nothing checks it.

This module extracts that schedule statically: for every function (and
transitively from every ``jit``/``shard_map`` root via tpulint's
call-graph walker) the ordered list of collective call sites —
``(op, kind, axis, operand, file, line)`` — in source-evaluation order.
``rules.py`` consumes per-function schedules; the CLI ``--schedule``
flag dumps the per-root walk for humans.

Shares tpulint's parsed-AST cache (``tools.tpulint.core``): running
both gates in one process parses every file once.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.tpulint.callgraph import (FunctionInfo, _callee_name,
                                     compute_traced)
from tools.tpulint.core import FileInfo

# XLA collective primitives issued inside traced code (jax.lax.*)
DEVICE_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "psum_scatter", "pshuffle", "pbroadcast",
}
# host-side collective primitives (DCN; one call per process)
HOST_PRIMITIVES = {"process_allgather"}
# package seam functions that PERFORM a host collective when called —
# calling these is the sanctioned way to touch the DCN (retry +
# telemetry + flight recorder ride along)
HOST_WRAPPERS = {"jax_process_allgather", "find_bins_distributed",
                 "merged_summary"}
# calls producing RANK-VARIANT values (process_count/axis_size are
# deliberately absent: they are uniform across ranks)
RANK_SOURCES = {"axis_index", "process_index"}


@dataclass(frozen=True)
class Entry:
    """One collective call site, in schedule order."""
    op: str                     # "psum", "process_allgather", ...
    kind: str                   # "device" | "host"
    axis: Optional[str]         # unparsed axis expression, if present
    operand: Optional[str]      # unparsed first operand (truncated)
    file: str                   # root-relative path
    line: int

    def render(self) -> str:
        ax = f" axis={self.axis}" if self.axis else ""
        opnd = f" operand={self.operand}" if self.operand else ""
        return f"{self.file}:{self.line}: {self.op}[{self.kind}]{ax}{opnd}"


def _unparse(node: ast.AST, limit: int = 40) -> Optional[str]:
    try:
        s = ast.unparse(node)
    except Exception:       # tpulint: disable=TPL006 -- best-effort label
        return None
    return s if len(s) <= limit else s[:limit - 3] + "..."


def collective_call(node: ast.AST) -> Optional[Tuple[str, str]]:
    """(op, kind) when ``node`` is a collective call, else None.  Name
    matching is deliberately coarse (tpulint's philosophy): a bare
    ``psum``/``all_gather`` callee counts wherever it appears."""
    if not isinstance(node, ast.Call):
        return None
    name = _callee_name(node.func)
    if name in DEVICE_COLLECTIVES:
        return name, "device"
    if name in HOST_PRIMITIVES:
        return name, "host"
    if name in HOST_WRAPPERS:
        return name, "host"
    if (name == "initialize" and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == "distributed"):
        return "distributed.initialize", "host"
    return None


def entry_for(node: ast.Call, fi: FileInfo) -> Optional[Entry]:
    ck = collective_call(node)
    if ck is None:
        return None
    op, kind = ck
    axis = None
    operand = None
    if kind == "device":
        if len(node.args) >= 2:
            axis = _unparse(node.args[1])
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis"):
                axis = _unparse(kw.value)
        if node.args:
            operand = _unparse(node.args[0])
    return Entry(op=op, kind=kind, axis=axis, operand=operand,
                 file=fi.rel, line=node.lineno)


def walk_own(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Evaluation-ordered walk of a function body EXCLUDING nested
    ``def`` subtrees but INCLUDING lambdas — a lambda handed to
    ``jax.tree.map`` executes inline in the enclosing schedule (the
    ``_sync_global_best`` pattern), a nested ``def`` is its own node.
    Calls yield AFTER their argument subtrees (operands evaluate
    first), matching runtime collective issue order."""
    for child in ast.iter_child_nodes(fn_node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield from walk_own(child)
        yield child


def function_schedule(info: FunctionInfo) -> List[Entry]:
    """Ordered collective entries issued directly by ``info``'s own
    body (nested defs excluded — they are separate schedule units)."""
    out: List[Entry] = []
    for node in walk_own(info.node):
        if isinstance(node, ast.Call):
            e = entry_for(node, info.fi)
            if e is not None:
                out.append(e)
    return out


def subtree_schedule(node: ast.AST, fi: FileInfo) -> List[Entry]:
    """Ordered collective entries under an arbitrary statement subtree
    (used for branch-schedule comparison), nested defs excluded."""
    out: List[Entry] = []
    for sub in walk_own(node):
        if isinstance(sub, ast.Call):
            e = entry_for(sub, fi)
            if e is not None:
                out.append(e)
    # the subtree ROOT itself (walk_own yields children only)
    if isinstance(node, ast.Call):
        e = entry_for(node, fi)
        if e is not None:
            out.append(e)
    return out


# -- rank-variance taint --------------------------------------------------
def _expr_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            if _callee_name(node.func) in RANK_SOURCES:
                return True
        elif (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                and node.id in tainted):
            return True
    return False


def _target_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in target.elts:
            out.extend(_target_names(el))
        return out
    return []


def rank_tainted(fn_node: ast.AST) -> Set[str]:
    """Local names carrying rank-variant values: assigned (directly or
    transitively) from ``axis_index()``/``process_index()``.  A simple
    fixpoint over straight-line assignments — deliberately coarse, no
    kill-set (a name once rank-variant stays suspect)."""
    tainted: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in walk_own(fn_node):
            value = None
            targets: List[str] = []
            if isinstance(node, ast.Assign):
                value = node.value
                for t in node.targets:
                    targets.extend(_target_names(t))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value = node.value
                targets = _target_names(node.target)
            elif isinstance(node, ast.AugAssign):
                value = node.value
                targets = _target_names(node.target)
            elif isinstance(node, ast.NamedExpr):
                value = node.value
                targets = _target_names(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                value = node.iter
                targets = _target_names(node.target)
            if value is None or not targets:
                continue
            if _expr_tainted(value, tainted):
                new = set(targets) - tainted
                if new:
                    tainted |= new
                    changed = True
    return tainted


def test_is_rank_dependent(test: ast.AST, tainted: Set[str]) -> bool:
    return _expr_tainted(test, tainted)


# -- collective-performing propagation ------------------------------------
def performing_functions(functions: Dict[str, FunctionInfo]) -> Set[str]:
    """Qualnames of functions that (transitively) issue a collective:
    own body contains one, or they call (by bare name — same coarse
    resolution as the traced-set walk) a performing function."""
    by_name: Dict[str, List[FunctionInfo]] = {}
    for info in functions.values():
        by_name.setdefault(info.name, []).append(info)
    performing: Set[str] = {
        q for q, info in functions.items() if function_schedule(info)}
    # reverse edges: callee name -> caller qualnames
    callers: Dict[str, List[str]] = {}
    for q, info in functions.items():
        for callee in info.called:
            callers.setdefault(callee, []).append(q)
    work = [functions[q].name for q in performing]
    while work:
        name = work.pop()
        for caller_q in callers.get(name, []):
            if caller_q not in performing:
                performing.add(caller_q)
                work.append(functions[caller_q].name)
    return performing


# -- root schedule walk (the CLI --schedule dump) -------------------------
def extract_schedule(root: FunctionInfo,
                     functions: Dict[str, FunctionInfo],
                     _visited: Optional[Set[str]] = None,
                     _depth: int = 0) -> List[Entry]:
    """Ordered collective schedule along every path from ``root``:
    own-body collectives in evaluation order, with calls to local
    functions expanded inline (coarse name resolution, cycle-guarded).
    This is the static mirror of what the runtime flight recorder
    (``lightgbm_tpu/obs/flight_recorder.py``) captures at trace time."""
    visited = _visited if _visited is not None else set()
    if root.qualname in visited or _depth > 12:
        return []
    visited.add(root.qualname)
    by_name: Dict[str, List[FunctionInfo]] = {}
    for info in functions.values():
        by_name.setdefault(info.name, []).append(info)
    out: List[Entry] = []
    for node in walk_own(root.node):
        if not isinstance(node, ast.Call):
            continue
        e = entry_for(node, root.fi)
        if e is not None:
            out.append(e)
            continue
        callee = _callee_name(node.func)
        if callee is None:
            continue
        # prefer same-file definitions; fall back to any package match
        cands = [i for i in by_name.get(callee, [])
                 if i.fi.rel == root.fi.rel] or by_name.get(callee, [])
        for info in cands[:1]:
            out.extend(extract_schedule(info, functions, visited,
                                        _depth + 1))
    return out


def schedule_roots(functions: Dict[str, FunctionInfo],
                   traced: Set[str]) -> List[FunctionInfo]:
    """Entry points worth dumping: jit/shard_map roots plus host
    collective seam functions (they anchor the host schedule)."""
    roots = [info for q, info in functions.items()
             if info.is_root and q in traced]
    roots += [info for info in functions.values()
              if info.name in HOST_WRAPPERS and not info.is_root]
    return sorted(roots, key=lambda i: (i.fi.rel, i.node.lineno))


def build_graph(files: Sequence[FileInfo]
                ) -> Tuple[Dict[str, FunctionInfo], Set[str], Set[str]]:
    """(functions by qualname, traced qualnames, performing qualnames) —
    one call-graph build shared by every rule."""
    functions, traced = compute_traced(files)
    return functions, traced, performing_functions(functions)
