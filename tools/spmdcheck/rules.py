"""spmdcheck rules SPM001-SPM004 — cross-rank schedule hazards.

tpulint (TPL001-TPL008) checks intra-rank hazards; these rules check
the property tpulint cannot see: that every rank issues the IDENTICAL
collective schedule.  The reference enforces it by construction —
every machine runs the same split sequence and blocking socket
collectives (`data_parallel_tree_learner.cpp:147-162`); a JAX port
desyncs silently when trace-time Python branches on the rank.

| id     | hazard                                                       |
|--------|--------------------------------------------------------------|
| SPM001 | collective under a rank-conditional branch (`axis_index`/    |
|        | `process_index`-dependent test): ranks can skip or reorder   |
|        | the schedule — deadlock or silent skew                       |
| SPM002 | sibling branches both reach collectives but with DIFFERENT   |
|        | (op, axis) sequences: whichever way the predicate resolves   |
|        | per rank, the schedules cannot both be right                 |
| SPM003 | rank-variant value feeding a collective operand SHAPE or a   |
|        | loop trip count that issues collectives: per-rank shape /    |
|        | call-count divergence (rank-variant VALUES are fine — that   |
|        | is what collectives are for)                                 |
| SPM004 | host collective primitive called outside the                 |
|        | io/distributed.py / parallel/mesh.py seam (loses retry,      |
|        | telemetry span, and flight-recorder fingerprinting)          |

Suppression syntax is shared with tpulint
(``# spmdcheck: disable=SPMxxx -- why`` or the ``tpulint:`` tag).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set

from tools.tpulint.callgraph import FunctionInfo, _callee_name
from tools.tpulint.core import FileInfo, Finding
from tools.tpulint.rules import JAX_ALIASES, NP_ALIASES, _root_name

from .schedule import (Entry, _expr_tainted, build_graph, entry_for,
                       rank_tainted, subtree_schedule,
                       test_is_rank_dependent, walk_own)

RULE_TITLES = {
    "SPM001": "collective under rank-conditional control flow",
    "SPM002": "sibling branches with mismatched collective schedules",
    "SPM003": "rank-variant value feeds collective shape/trip count",
    "SPM004": "host collective outside the retry/telemetry seam",
}

# the sanctioned host-collective seam modules (retry + span + flight
# recorder wrap every primitive there)
SEAM_SUFFIXES = ("io/distributed.py", "parallel/mesh.py")

_SHAPE_FNS = {"zeros", "ones", "full", "empty", "arange", "broadcast_to",
              "tile", "repeat", "reshape"}


@dataclass
class SpmdContext:
    root: str
    files: List[FileInfo]
    by_rel: Dict[str, FileInfo]
    functions: Dict[str, FunctionInfo]
    traced: Set[str]
    performing: Set[str]            # qualnames issuing collectives


def build_context(files: Sequence[FileInfo], root: str) -> SpmdContext:
    functions, traced, performing = build_graph(files)
    return SpmdContext(root=root, files=list(files),
                       by_rel={fi.rel: fi for fi in files},
                       functions=functions, traced=traced,
                       performing=performing)


def _file_functions(fi: FileInfo, ctx: SpmdContext) -> List[FunctionInfo]:
    return [info for info in ctx.functions.values() if info.fi.rel == fi.rel]


class _ModuleScope:
    """Module-level statements as a pseudo-function (a rank-guarded host
    collective at import/module scope is the same hazard)."""

    def __init__(self, fi: FileInfo):
        self.fi = fi
        self.node = fi.tree
        self.name = "<module>"
        self.qualname = f"{fi.rel}::<module>"


def _scopes(fi: FileInfo, ctx: SpmdContext):
    return [_ModuleScope(fi)] + _file_functions(fi, ctx)


# -- SPM001 ---------------------------------------------------------------
def rule_spm001(fi: FileInfo, ctx: SpmdContext) -> List[Finding]:
    out: List[Finding] = []
    for scope in _scopes(fi, ctx):
        tainted = rank_tainted(scope.node)

        def visit(node: ast.AST, cond_line: Optional[int]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue        # separate scope
                branch_cond = cond_line
                if isinstance(child, (ast.If, ast.While)):
                    visit(child.test, cond_line)
                    if test_is_rank_dependent(child.test, tainted):
                        branch_cond = child.lineno
                    for stmt in list(child.body) + list(child.orelse):
                        visit(stmt, branch_cond)
                    continue
                if isinstance(child, ast.IfExp):
                    visit(child.test, cond_line)
                    sub = (child.lineno
                           if test_is_rank_dependent(child.test, tainted)
                           else cond_line)
                    visit(child.body, sub)
                    visit(child.orelse, sub)
                    continue
                _check(child, branch_cond)
                visit(child, branch_cond)

        def _check(node: ast.AST, cond_line: Optional[int]) -> None:
            if cond_line is None or not isinstance(node, ast.Call):
                return
            e = entry_for(node, fi)
            if e is not None:
                out.append(Finding(
                    fi.rel, node.lineno, "SPM001",
                    f"collective `{e.op}` under a rank-conditional "
                    f"branch (test at line {cond_line}): ranks take "
                    f"different schedules — deadlock or silent skew; "
                    f"hoist the collective out of the branch, or make "
                    f"every rank issue it and mask the result"))

        visit(scope.node, None)
    return out


# -- SPM002 ---------------------------------------------------------------
def _seq_sig(entries: List[Entry]) -> List[str]:
    return [f"{e.op}@{e.axis or '?'}" for e in entries]


def rule_spm002(fi: FileInfo, ctx: SpmdContext) -> List[Finding]:
    out: List[Finding] = []
    for scope in _scopes(fi, ctx):
        for node in walk_own(scope.node):
            if not isinstance(node, ast.If) or not node.orelse:
                continue
            body_seq: List[Entry] = []
            for stmt in node.body:
                body_seq.extend(subtree_schedule(stmt, fi))
            else_seq: List[Entry] = []
            for stmt in node.orelse:
                else_seq.extend(subtree_schedule(stmt, fi))
            if not body_seq or not else_seq:
                continue
            bs, es = _seq_sig(body_seq), _seq_sig(else_seq)
            if bs != es:
                out.append(Finding(
                    fi.rel, node.lineno, "SPM002",
                    f"sibling branches reach different collective "
                    f"schedules ({' -> '.join(bs)} vs "
                    f"{' -> '.join(es)}): if the predicate can differ "
                    f"across ranks the schedules desync; make the "
                    f"branches issue the same (op, axis) sequence or "
                    f"lift the collectives above the branch"))
    return out


# -- SPM003 ---------------------------------------------------------------
def _subtree_has_collective(node: ast.AST, fi: FileInfo,
                            ctx: SpmdContext) -> bool:
    if subtree_schedule(node, fi):
        return True
    # calls to collective-performing package functions count too
    performing_names = {ctx.functions[q].name for q in ctx.performing}
    for sub in walk_own(node):
        if isinstance(sub, ast.Call):
            if _callee_name(sub.func) in performing_names:
                return True
    return False


def _resolves_to_performing(arg: ast.AST, fi: FileInfo,
                            ctx: SpmdContext) -> bool:
    name = _callee_name(arg) if not isinstance(arg, ast.Name) else arg.id
    if name is None:
        return False
    return any(ctx.functions[q].name == name for q in ctx.performing)


def rule_spm003(fi: FileInfo, ctx: SpmdContext) -> List[Finding]:
    out: List[Finding] = []
    for scope in _scopes(fi, ctx):
        tainted = rank_tainted(scope.node)
        if not tainted:
            continue
        performs = (isinstance(scope, FunctionInfo)
                    and scope.qualname in ctx.performing) \
            or bool(subtree_schedule(scope.node, fi)
                    if isinstance(scope, _ModuleScope) else False)
        for node in walk_own(scope.node):
            # (a) Python loop with a rank-variant trip count ISSUING
            # collectives: per-rank collective counts diverge
            if isinstance(node, (ast.For, ast.AsyncFor)):
                it = node.iter
                if (isinstance(it, ast.Call)
                        and _callee_name(it.func) == "range"
                        and any(_is_tainted(a, tainted) for a in it.args)
                        and any(_subtree_has_collective(s, fi, ctx)
                                for s in node.body)):
                    out.append(Finding(
                        fi.rel, node.lineno, "SPM003",
                        "loop trip count is rank-variant and the body "
                        "issues collectives: ranks issue different "
                        "collective counts and desync; make the trip "
                        "count uniform (sync a max first) or move the "
                        "collective out of the loop"))
            elif isinstance(node, ast.Call):
                callee = _callee_name(node.func)
                # (b) traced loop combinators with rank-variant trip
                # counts around collective-issuing bodies
                if callee in ("fori_loop", "scan", "while_loop"):
                    bounds = list(node.args[:2])
                    bounds += [kw.value for kw in node.keywords
                               if kw.arg == "length"]
                    body_args = [a for a in node.args[2:3]] or node.args[:1]
                    if (any(_is_tainted(b, tainted) for b in bounds)
                            and (performs
                                 or any(_resolves_to_performing(a, fi, ctx)
                                        for a in body_args))):
                        out.append(Finding(
                            fi.rel, node.lineno, "SPM003",
                            f"`{callee}` trip count is rank-variant in "
                            f"collective-issuing code: per-rank "
                            f"schedules diverge; bound the loop by a "
                            f"synced (uniform) count"))
                # (c) rank-variant shape construction feeding the
                # collective path (operand shapes must match rank-wide)
                elif (callee in _SHAPE_FNS and performs
                      and isinstance(node.func, ast.Attribute)
                      and _root_name(node.func) in (NP_ALIASES
                                                    | JAX_ALIASES)):
                    shape_args = list(node.args[:1]) if callee != "arange" \
                        else list(node.args)
                    shape_args += [kw.value for kw in node.keywords
                                   if kw.arg == "shape"]
                    if any(_is_tainted(a, tainted) for a in shape_args):
                        out.append(Finding(
                            fi.rel, node.lineno, "SPM003",
                            f"`{callee}` builds a rank-variant SHAPE in "
                            f"collective-issuing code: collective "
                            f"operand shapes must be identical on every "
                            f"rank (XLA rejects the lucky ones, DCN "
                            f"corrupts the rest); pad to a synced max "
                            f"like io/distributed.py does"))
    return out


def _is_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
    return _expr_tainted(expr, tainted)


# -- SPM004 ---------------------------------------------------------------
def rule_spm004(fi: FileInfo, ctx: SpmdContext) -> List[Finding]:
    if fi.rel.endswith(SEAM_SUFFIXES):
        return []
    out: List[Finding] = []
    for node in ast.walk(fi.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node.func)
        prim = None
        if name == "process_allgather":
            prim = "multihost_utils.process_allgather"
        elif (name == "initialize" and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == "distributed"):
            prim = "jax.distributed.initialize"
        if prim is not None:
            out.append(Finding(
                fi.rel, node.lineno, "SPM004",
                f"{prim} called outside the io/distributed.py / "
                f"parallel/mesh.py seam: the call skips the shared "
                f"retry policy, the telemetry span, and the flight-"
                f"recorder fingerprint; route through "
                f"jax_process_allgather / init_distributed"))
    return out


FILE_RULES: List[Callable[[FileInfo, SpmdContext], List[Finding]]] = [
    rule_spm001, rule_spm002, rule_spm003, rule_spm004,
]
