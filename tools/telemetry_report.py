#!/usr/bin/env python
"""Render a phase-breakdown table from a telemetry JSONL trace.

Usage::

    python tools/telemetry_report.py /tmp/run.jsonl [more.jsonl ...]
    python tools/telemetry_report.py /tmp/run.jsonl.summary.json

Reads trace files written via ``LGBM_TPU_TRACE=<path>`` or the
``telemetry_output`` config parameter (multi-host runs write one
``<path>.rank<k>`` file per rank — pass them all to merge).  Prints:

* per-span phase breakdown (count, total seconds, share of the summed
  span time at that nesting depth, max single duration),
* counters (retry attempts/backoff, snapshot bytes, compile counts...),
* one-shot events (faults fired, early stopping).

The share column uses DEPTH-0 spans as the denominator: nested spans
(e.g. ``gbdt.block`` inside ``gbdt.train`` inside ``engine.train``)
would otherwise double-count wall-clock.  See README "Observability"
for the event schema.

A ``*.summary.json`` argument (one JSON object, not JSONL) is
rendered from the summary side instead — including the
``device_attribution`` section a ``LGBM_TPU_PROFILE`` run attaches
(per-span DEVICE time, host gap, roofline columns), via
``tools/perf_report.py``.
"""
import json
import sys
from collections import defaultdict


def load_records(paths):
    records = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
    return records


def health_block(events, counters, state=None, ranks=None,
                 out=sys.stdout):
    """The "Health" section (ISSUE 13): live-plane state, watchdog
    arms/fires, sentinel trips, and every ``health:*`` event — the
    post-hoc rendering of what ``/healthz`` + ``/metrics`` served
    live.  Skipped entirely when the run carried no health signal."""
    h_events = {k: v for k, v in events.items() if k.startswith("health:")}
    h_counters = {k: v for k, v in counters.items()
                  if k.startswith(("watchdog.", "health."))}
    if not (h_events or h_counters or state or ranks):
        return
    print("\n== health ==", file=out)
    if state:
        det = state.get("detail") or {}
        extra = (" (" + ", ".join(f"{k}={v}" for k, v in det.items())
                 + ")") if det else ""
        print(f"  state: {state.get('state', '?')}{extra}", file=out)
    if ranks:
        per = ", ".join(f"rank{r}={s or '?'}"
                        for r, s in enumerate(ranks.get("ranks", [])))
        print(f"  per-rank: {per}    worst: {ranks.get('worst')}",
              file=out)
    arms = int(h_counters.get("watchdog.arms", 0))
    fires = int(h_counters.get("watchdog.fires", 0))
    if arms or fires:
        print(f"  watchdog: {arms} arm(s), {fires} fire(s)", file=out)
    checks = int(h_counters.get("health.sentinel_checks", 0))
    trips = (int(h_counters.get("health.nonfinite", 0))
             + int(h_counters.get("health.loss_spikes", 0)))
    if checks or trips:
        print(f"  sentinels: {checks} check(s), {trips} trip(s)",
              file=out)
    for name in sorted(h_events):
        print(f"  {name:<38s} {h_events[name]:>12d}", file=out)


def report(records, out=sys.stdout):
    spans = defaultdict(lambda: [0, 0.0, 0.0, 0])   # count,total,max,min_depth
    counters = {}
    events = defaultdict(int)
    ranks = set()
    for r in records:
        ranks.add(r.get("rank", 0))
        kind = r.get("kind")
        if kind == "span":
            agg = spans[r["name"]]
            agg[0] += 1
            agg[1] += r.get("dur_s", 0.0)
            agg[2] = max(agg[2], r.get("dur_s", 0.0))
            agg[3] = min(agg[3], r.get("depth", 0)) if agg[0] > 1 \
                else r.get("depth", 0)
        elif kind == "counter":
            counters[r["name"]] = r.get("value", 0)
        elif kind == "event":
            events[f'{r.get("family", "event")}:{r["name"]}'] += 1

    wall = sum(v[1] for v in spans.values() if v[3] == 0) or 1.0
    print(f"ranks: {sorted(ranks)}    depth-0 span time: {wall:.3f}s",
          file=out)
    print(f"\n{'phase':<28s} {'count':>7s} {'total_s':>10s} "
          f"{'share':>7s} {'max_s':>9s}", file=out)
    print("-" * 64, file=out)
    for name, (cnt, total, mx, depth) in sorted(
            spans.items(), key=lambda kv: -kv[1][1]):
        share = f"{100.0 * total / wall:5.1f}%" if depth == 0 else "     -"
        indent = "  " * depth
        print(f"{indent + name:<28s} {cnt:>7d} {total:>10.3f} "
              f"{share:>7s} {mx:>9.3f}", file=out)
    if counters:
        print("\ncounters:", file=out)
        for name in sorted(counters):
            v = counters[name]
            v = f"{v:.3f}" if isinstance(v, float) and v != int(v) \
                else f"{int(v)}"
            print(f"  {name:<40s} {v:>12s}", file=out)
    if events:
        print("\nevents:", file=out)
        for name in sorted(events):
            print(f"  {name:<40s} {events[name]:>12d}", file=out)
    health_block(events, counters, out=out)


def _try_summary(path):
    """-> a summary dict when ``path`` holds ONE JSON object (the
    ``.summary.json`` surface), else None (JSONL traces parse line-wise)."""
    try:
        with open(path) as f:
            data = json.load(f)
        return data if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None


def collective_skew_block(sk, out=sys.stdout):
    """The "collective skew" section (ISSUE 17): per-site arrival-wait
    accounting.  Renders both shapes — a single-rank summary carries
    this rank's wait/xfer totals; a merged summary carries the
    side-by-side per-rank table with the dominant straggler."""
    if not sk:
        return
    print("\n== collective skew ==", file=out)
    for site in sorted(sk):
        st = sk[site]
        if "per_rank_wait_s" in st:     # merged (fleet) shape
            waits = ", ".join(f"r{r}={w:.3f}s" for r, w in
                              enumerate(st.get("per_rank_wait_s", [])))
            line = (f"  {site:<34s} waves={st.get('waves', 0):<5d} "
                    f"wait[{waits}] max={st.get('wait_max_s', 0.0):.3f}s")
            if "straggler_rank" in st:
                line += (f"  straggler: rank {st['straggler_rank']} "
                         f"({st.get('straggler_pct', 0.0):.0f}% of waves)")
            print(line, file=out)
        else:                           # single-rank shape
            print(f"  {site:<34s} waves={st.get('waves', 0):<5d} "
                  f"wait={st.get('wait_total_s', 0.0):.3f}s "
                  f"xfer={st.get('xfer_total_s', 0.0):.3f}s "
                  f"max_wait={st.get('wait_max_s', 0.0):.3f}s "
                  f"straggler_waves={st.get('straggler_waves', 0)}",
                  file=out)


def report_summary(s, out=sys.stdout):
    """Host-side span table from a summary dict, then the device-time
    attribution section when the run was profiled."""
    spans = s.get("spans", {})
    total = sum(v.get("total_s", 0.0) for v in spans.values()) or 1.0
    print(f"summary: rank {s.get('rank', '?')} / "
          f"{s.get('process_count', '?')} process(es)", file=out)
    print(f"\n{'span':<28s} {'count':>7s} {'total_s':>10s} {'max_s':>9s}",
          file=out)
    print("-" * 58, file=out)
    for name, v in sorted(spans.items(), key=lambda kv: -kv[1]["total_s"]):
        print(f"{name:<28s} {v['count']:>7d} {v['total_s']:>10.3f} "
              f"{v['max_s']:>9.3f}", file=out)
    # Health section: a single-rank summary carries its own `health`
    # state; a merged multi-rank summary carries the per-rank lift
    # (telemetry.merged_summary) — both render here
    hstate = s.get("health") if "state" in (s.get("health") or {}) else None
    hranks = s.get("health") if "ranks" in (s.get("health") or {}) else None
    health_block(s.get("events", {}), s.get("counters", {}),
                 state=hstate, ranks=hranks, out=out)
    collective_skew_block(s.get("collective_skew"), out=out)
    da = s.get("device_attribution")
    if da:
        print("\n== device attribution (LGBM_TPU_PROFILE capture) ==",
              file=out)
        try:
            from tools.perf_report import render
        except ImportError:     # invoked as `python tools/telemetry_report.py`
            from perf_report import render
        render(da, out=out)


def main(argv):
    if not argv:
        print(__doc__)
        return 1
    summaries = [p for p in argv if _try_summary(p) is not None]
    traces = [p for p in argv if p not in summaries]
    for p in summaries:
        report_summary(_try_summary(p))
    if traces:
        report(load_records(traces))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
