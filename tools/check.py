"""Umbrella static gate: ``python -m tools.check [--root R] [paths...]``.

Runs all six analyzers — tpulint (TPL000-TPL008), spmdcheck
(SPM001-SPM004), memcheck (MEM001-MEM005), detcheck (DET001-DET006),
concheck (CON000-CON006), numcheck (NUM000-NUM005) — over ONE shared
AST parse (``tools/analysis_core.py``'s process-wide
cache: each file is parsed exactly once no matter how many analyzers
visit it) and diffs each against its own committed baseline.  Exit 0 =
all clean, 1 = any new finding, 2 = usage error.

numcheck additionally sweeps ``tests/`` (tolerance-literal discipline
lives in test files) when the default package path is analyzed.

This is what the tier-1 gate tests call (``tests/test_tpulint.py`` /
``test_spmdcheck.py`` / ``test_memcheck.py`` / ``test_detcheck.py``
share one in-process :func:`cached_run_all`), and the one command a
developer needs before pushing::

    python -m tools.check

Per-analyzer CLIs remain for focused work (``--update-baseline``,
``--schedule``, ``--footprint`` live there).
"""
from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from tools.analysis_core import Finding, load_baseline, new_findings


def run_all(paths: Sequence[str] = ("lightgbm_tpu",),
            root: Optional[str] = None,
            project_rules: bool = True,
            ) -> Dict[str, Tuple[List[Finding], List[Finding]]]:
    """Run the six analyzers over one parse; -> name ->
    (all_findings, new_vs_baseline)."""
    from tools.concheck import (BASELINE_DEFAULT as CON_BL, run_concheck)
    from tools.detcheck import (BASELINE_DEFAULT as DET_BL, run_detcheck)
    from tools.memcheck import (BASELINE_DEFAULT as MEM_BL, run_memcheck)
    from tools.numcheck import (BASELINE_DEFAULT as NUM_BL, run_numcheck)
    from tools.spmdcheck import (BASELINE_DEFAULT as SPM_BL, run_spmdcheck)
    from tools.tpulint import (BASELINE_DEFAULT as TPL_BL, run_lint)
    root = os.path.abspath(root or os.getcwd())
    # numcheck's NUM004 (tolerance discipline) lives in test files: when
    # the stock package path is analyzed, extend its sweep to tests/
    num_paths = tuple(paths)
    if num_paths == ("lightgbm_tpu",) \
            and os.path.isdir(os.path.join(root, "tests")):
        num_paths = num_paths + ("tests",)
    out: Dict[str, Tuple[List[Finding], List[Finding]]] = {}
    for name, runner, bl in (
            ("tpulint",
             lambda: run_lint(paths, root=root, project_rules=project_rules),
             TPL_BL),
            ("spmdcheck", lambda: run_spmdcheck(paths, root=root), SPM_BL),
            ("memcheck",
             lambda: run_memcheck(paths, root=root,
                                  project_rules=project_rules),
             MEM_BL),
            ("detcheck",
             lambda: run_detcheck(paths, root=root,
                                  project_rules=project_rules),
             DET_BL),
            ("concheck",
             lambda: run_concheck(paths, root=root,
                                  project_rules=project_rules),
             CON_BL),
            ("numcheck",
             lambda: run_numcheck(num_paths, root=root,
                                  project_rules=project_rules),
             NUM_BL)):
        findings, by_rel = runner()
        baseline = load_baseline(os.path.join(root, bl))
        out[name] = (findings, new_findings(findings, by_rel, baseline))
    return out


# one shared analysis per (root, paths) per process: the three tier-1
# gate tests each assert their own analyzer's verdict off this cache,
# so a pytest session pays for ONE parse + analysis pass, not three
_RUN_CACHE: Dict[Tuple[str, Tuple[str, ...]], Dict] = {}


def cached_run_all(root: str, paths: Sequence[str] = ("lightgbm_tpu",)
                   ) -> Dict[str, Tuple[List[Finding], List[Finding]]]:
    key = (os.path.abspath(root), tuple(paths))
    if key not in _RUN_CACHE:
        _RUN_CACHE[key] = run_all(paths, root=root)
    return _RUN_CACHE[key]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.check",
        description="combined static gate: tpulint + spmdcheck + "
                    "memcheck + detcheck + concheck + numcheck over "
                    "one shared AST parse")
    parser.add_argument("paths", nargs="*", default=["lightgbm_tpu"])
    parser.add_argument("--root", default=None,
                        help="project root (default: cwd)")
    parser.add_argument("--no-project-rules", action="store_true",
                        help="skip repo-level rules (TPL005/TPL008 "
                             "doc+oracle checks, MEM003 footprint gate)")
    args = parser.parse_args(argv)
    root = os.path.abspath(args.root or os.getcwd())
    t0 = time.perf_counter()
    try:
        results = run_all(args.paths or ["lightgbm_tpu"], root=root,
                          project_rules=not args.no_project_rules)
    except OSError as exc:
        print(f"check: {exc}", file=sys.stderr)
        return 2
    rc = 0
    for name, (findings, fresh) in results.items():
        for f in fresh:
            print(f.render())
        pinned = len(findings) - len(fresh)
        if fresh:
            rc = 1
            print(f"{name}: {len(fresh)} new finding(s)"
                  + (f" ({pinned} baselined)" if pinned else ""))
        else:
            print(f"{name}: clean"
                  + (f" ({pinned} baselined)" if pinned else ""))
    print(f"check: {'FAIL' if rc else 'ok'} "
          f"({time.perf_counter() - t0:.2f}s, one shared parse)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
