#!/usr/bin/env python
"""Merge per-rank telemetry traces + the coordinator ledger into one
fleet view (ISSUE 17): causally-ordered timeline, per-site straggler
attribution, recovery MTTR breakdown, chrome-trace export.

Usage::

    python tools/fleet_report.py trace.jsonl.rank0 trace.jsonl.rank1 \\
        [--ledger fleet.jsonl] [--chrome out.json] [--json] [--eps 0.25]

Inputs are the JSONL traces written via ``LGBM_TPU_TRACE`` (one
``.rank<k>`` file per rank) and, optionally, the coordinator's fleet
ledger (``LGBM_TPU_FLEET_LEDGER``).  What the merge relies on:

* every record may carry ``clk_off_s`` — the rank's coordinator-clock
  offset (midpoint-of-RTT, ``obs/fleet.py``); corrected time is
  ``ts + clk_off_s``, putting all ranks AND the ledger on one clock;
* host-collective spans carry the join key ``(site, generation, seq)``
  plus ``wait_s`` / ``xfer_s`` / ``arrive_ts`` / ``straggler_rank``,
  so per-rank records of the same collective join exactly.

Sections of the report:

* ``skew`` — per site: waves, p50/p99 arrival skew (the max wait of a
  wave), and the straggler histogram ("rank 2 last into hist_psum 87%
  of waves").  Needs no clock agreement at all: each wave's straggler
  is named consistently on every rank by the collective itself.
* ``monotone`` — the offset-correction audit: within every joined
  collective, each rank's corrected span must OVERLAP the wave's
  arrival window (a collective span cannot end before the last rank
  arrived).  Violations beyond ``--eps`` (clock error bound + pipe
  slack) mean the offsets are wrong, not the fleet.
* ``recovery`` — every ``elastic:recovery`` event: per-phase
  ``detect/resync/reshard/restore/retrain`` durations and the check
  that they sum to ``mttr_s`` (they do by construction; the report
  re-verifies from the records).
* ``ledger`` — the coordinator's own history (joins, evictions,
  generation bumps, completed rounds), merged into the timeline as
  its own track.

``--chrome`` writes a Chrome-trace JSON loadable in Perfetto /
``chrome://tracing``: one track (pid) per rank plus a coordinator
track, span records as complete ("X") events on the corrected clock.
"""
import argparse
import json
import sys
from collections import defaultdict


def load_traces(paths):
    records = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                rec["_src"] = path
                records.append(rec)
    return records


def load_ledger(path):
    try:
        from lightgbm_tpu.obs.fleet import read_ledger
    except ImportError:
        sys.path.insert(0, ".")
        from lightgbm_tpu.obs.fleet import read_ledger
    return read_ledger(path)


def corrected_ts(rec):
    """ts on the coordinator clock: ts + clk_off_s (0 when unstamped —
    a rank that never synced is assumed already aligned)."""
    return float(rec.get("ts", 0.0)) + float(rec.get("clk_off_s", 0.0))


def corrected_arrive(rec):
    """The record's arrival stamp on the coordinator clock.  Elastic
    collectives stamp ``arrive_ts`` FROM the coordinator's clock
    (no correction); io.distributed collectives stamp it from the
    local clock (corrected like ``ts``)."""
    a = rec.get("arrive_ts")
    if a is None:
        return None
    if str(rec.get("site", "")).startswith("elastic."):
        return float(a)
    return float(a) + float(rec.get("clk_off_s", 0.0))


def _is_collective(rec):
    return (rec.get("kind") == "span" and "site" in rec
            and "seq" in rec and "wait_s" in rec)


def _pct(values, q):
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(int(round(q * (len(vals) - 1))), len(vals) - 1)
    return vals[idx]


def build_report(records, ledger=None, eps=0.25):
    # -- join collectives on (site, generation, seq) -------------------
    groups = defaultdict(list)
    for r in records:
        if _is_collective(r):
            key = (r["site"], int(r.get("generation", -1)),
                   int(r["seq"]))
            groups[key].append(r)

    per_site = defaultdict(lambda: {"waves": 0, "skew_s": [],
                                    "stragglers": defaultdict(int)})
    violations = []
    checked = 0
    for (site, gen, seq), recs in sorted(groups.items()):
        st = per_site[site]
        st["waves"] += 1
        # wave skew = the max wait anyone spent blocked on peers; the
        # straggler is named identically on every rank's record (it
        # came from the shared arrival list), so take any
        st["skew_s"].append(max(float(r.get("wait_s", 0.0))
                                for r in recs))
        strag = recs[0].get("straggler_rank")
        if strag is None:
            strag = min(recs, key=lambda r: float(r.get("wait_s", 0.0))
                        ).get("rank", -1)
        st["stragglers"][int(strag)] += 1
        # monotonicity audit: every rank's corrected span must overlap
        # the wave's arrival window (no record may END before the last
        # arrival it claims to have waited for)
        arrivals = [corrected_arrive(r) for r in recs]
        arrivals = [a for a in arrivals if a is not None]
        if len(arrivals) >= 2:
            checked += 1
            last_arrive = max(arrivals)
            for r in recs:
                end = corrected_ts(r) + float(r.get("dur_s", 0.0))
                a = corrected_arrive(r)
                start = corrected_ts(r)
                bad = (end + eps < last_arrive
                       or (a is not None
                           and not (start - eps <= a <= end + eps)))
                if bad:
                    violations.append({
                        "site": site, "generation": gen, "seq": seq,
                        "rank": r.get("rank", -1),
                        "start": start, "end": end, "arrive": a,
                        "last_arrive": last_arrive,
                    })

    skew = {}
    for site, st in per_site.items():
        hist = dict(sorted(st["stragglers"].items()))
        total = sum(hist.values()) or 1
        top = max(hist, key=lambda r: hist[r]) if hist else -1
        skew[site] = {
            "waves": st["waves"],
            "skew_p50_s": round(_pct(st["skew_s"], 0.50), 6),
            "skew_p99_s": round(_pct(st["skew_s"], 0.99), 6),
            "straggler_hist": {str(r): c for r, c in hist.items()},
            "straggler_rank": int(top),
            "straggler_pct": round(100.0 * hist.get(top, 0) / total, 1),
        }

    # -- recovery episodes (elastic:recovery events) -------------------
    episodes = []
    phase_keys = ("detect_s", "resync_s", "reshard_s", "restore_s",
                  "retrain_s")
    for r in records:
        if r.get("kind") == "event" and r.get("family") == "elastic" \
                and r.get("name") == "recovery":
            phases = {k: float(r.get(k, 0.0)) for k in phase_keys}
            mttr = float(r.get("mttr_s", 0.0))
            episodes.append({
                "rank": r.get("rank", -1),
                "error": r.get("error", ""),
                "generation": r.get("generation", -1),
                "target_iter": r.get("target_iter", 0),
                "mttr_s": mttr,
                "phases": phases,
                "phases_sum_ok": abs(sum(phases.values()) - mttr) < 1e-6,
            })

    # -- clock offsets (what the correction used) ----------------------
    clocks = {}
    for r in records:
        if "clk_off_s" in r:
            clocks[str(r.get("rank", -1))] = float(r["clk_off_s"])

    report = {
        "ranks": sorted({r.get("rank", 0) for r in records}),
        "records": len(records),
        "collectives": {"sites": len(skew),
                        "waves": sum(s["waves"] for s in skew.values()),
                        "joined": len(groups)},
        "clock_offsets_s": clocks,
        "skew": skew,
        "monotone": {"ok": not violations, "checked": checked,
                     "eps_s": eps, "violations": violations[:20]},
        "recovery": {"episodes": episodes,
                     "ok": all(e["phases_sum_ok"] for e in episodes)},
    }
    if ledger is not None:
        kinds = defaultdict(int)
        for e in ledger:
            kinds[e.get("kind", "?")] += 1
        report["ledger"] = {"events": len(ledger), "kinds": dict(kinds)}
    return report


def chrome_trace(records, ledger=None):
    """Chrome-trace JSON (Perfetto-loadable): one pid per rank, span
    records as complete events on the corrected (coordinator) clock,
    ledger entries as instant events on a coordinator track."""
    events = []
    for r in records:
        if r.get("kind") != "span":
            continue
        rank = int(r.get("rank", 0))
        events.append({
            "name": r.get("name", "?"),
            "cat": r.get("site", "span"),
            "ph": "X",
            "ts": corrected_ts(r) * 1e6,
            "dur": float(r.get("dur_s", 0.0)) * 1e6,
            "pid": rank, "tid": int(r.get("depth", 0)),
            "args": {k: v for k, v in r.items()
                     if k not in ("kind", "name", "ts", "dur_s")},
        })
    meta = [{"name": "process_name", "ph": "M", "pid": p,
             "args": {"name": f"rank {p}"}}
            for p in sorted({e["pid"] for e in events})]
    if ledger:
        COORD_PID = 10_000
        meta.append({"name": "process_name", "ph": "M",
                     "pid": COORD_PID, "args": {"name": "coordinator"}})
        for e in ledger:
            events.append({
                "name": e.get("kind", "?"), "cat": "ledger", "ph": "i",
                "ts": float(e.get("ts", 0.0)) * 1e6, "s": "g",
                "pid": COORD_PID, "tid": 0,
                "args": {k: v for k, v in e.items()
                         if k not in ("kind", "ts")},
            })
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def render(report, out=sys.stdout):
    print(f"ranks: {report['ranks']}    records: {report['records']}",
          file=out)
    co = report["collectives"]
    print(f"collectives: {co['waves']} waves over {co['sites']} sites "
          f"({co['joined']} joined keys)", file=out)
    if report["clock_offsets_s"]:
        offs = ", ".join(f"r{r}={o:+.4f}s" for r, o in
                         sorted(report["clock_offsets_s"].items()))
        print(f"clock offsets: {offs}", file=out)
    if report["skew"]:
        print("\n== straggler attribution ==", file=out)
        for site in sorted(report["skew"]):
            s = report["skew"][site]
            print(f"  {site:<34s} waves={s['waves']:<5d} "
                  f"skew p50={s['skew_p50_s']:.3f}s "
                  f"p99={s['skew_p99_s']:.3f}s   straggler: rank "
                  f"{s['straggler_rank']} ({s['straggler_pct']:.0f}% "
                  f"of waves)", file=out)
    mono = report["monotone"]
    state = "OK" if mono["ok"] else \
        f"{len(mono['violations'])} violation(s)"
    print(f"\ntimeline monotone per collective: {state} "
          f"({mono['checked']} checked, eps={mono['eps_s']}s)", file=out)
    eps = report["recovery"]["episodes"]
    if eps:
        print("\n== recovery episodes ==", file=out)
        for e in eps:
            ph = "  ".join(f"{k[:-2]}={v:.3f}s"
                           for k, v in e["phases"].items())
            ok = "" if e["phases_sum_ok"] else "  [SUM MISMATCH]"
            print(f"  rank {e['rank']} {e['error']:<18s} "
                  f"mttr={e['mttr_s']:.3f}s  {ph}{ok}", file=out)
    if "ledger" in report:
        led = report["ledger"]
        kinds = ", ".join(f"{k}={v}" for k, v in
                          sorted(led["kinds"].items()))
        print(f"\nledger: {led['events']} event(s): {kinds}", file=out)


def main(argv):
    ap = argparse.ArgumentParser(
        description="merge per-rank traces + coordinator ledger into "
                    "one fleet report")
    ap.add_argument("traces", nargs="+", help="per-rank JSONL traces")
    ap.add_argument("--ledger", help="coordinator fleet ledger (JSONL)")
    ap.add_argument("--chrome", help="write chrome-trace JSON here")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON instead of text")
    ap.add_argument("--eps", type=float, default=0.25,
                    help="monotonicity slack (clock error bound), "
                         "seconds")
    args = ap.parse_args(argv)
    records = load_traces(args.traces)
    ledger = load_ledger(args.ledger) if args.ledger else None
    report = build_report(records, ledger=ledger, eps=args.eps)
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(chrome_trace(records, ledger), f)
        print(f"chrome trace written: {args.chrome}", file=sys.stderr)
    if args.json:
        json.dump(report, sys.stdout, indent=1)
        print()
    else:
        render(report)
    return 0 if (report["monotone"]["ok"]
                 and report["recovery"]["ok"]) else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
